"""Per-query execution profiles: the phase waterfall.

Role of the reference's "quickwit observes quickwit" loop
(`quickwit-telemetry` + per-request `tracing` spans): a single query can be
asked *where it spent its time* — plan build, HBM admission wait, batcher
queue wait, storage reads (bytes + hedged retries), host→device staging,
XLA compile vs execute (with compile-cache hit/miss), top-K merge, pruning
decisions, root merge — instead of only moving coarse counters.

A `QueryProfile` is created at root admission (or at the leaf entry point
for remote leaves) and travels ambiently through the stack via a
`contextvars.ContextVar`, mirroring `common/deadline.py` exactly: deep
layers (admission, storage wrappers, the executor) report into
`current_profile()` with no signature changes, and thread-pool hops rebind
with `bind_profile`. When no profile is bound — the default — every hook is
one ContextVar get returning None: no phase objects are allocated on the
hot path.

Each recorded phase also opens a span on the process tracer
(`observability/tracing.py`) so the same waterfall stitches into OTLP
traces, and phase durations feed the `qw_search_phase_seconds` histogram
(labeled by phase) so fleet-wide attribution is queryable without
capturing any single profile.
"""

from __future__ import annotations

import contextvars
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Optional

from .metrics import SEARCH_PHASE_SECONDS

# Canonical phase names (used by search/*, storage/*, serve/*). Keeping them
# here makes the waterfall schema greppable in one place; ad-hoc names are
# still allowed for one-off experiments.
PHASE_PLAN_BUILD = "plan_build"
PHASE_ADMISSION_WAIT = "admission_wait"
PHASE_BATCHER_QUEUE = "batcher_queue_wait"
# group-formation wait: time a rider spent queued while a multi-QUERY
# stacked group assembled around it (search/batcher.py QueryGroupPlanner);
# recorded INSTEAD of batcher_queue_wait for riders that dispatched as part
# of a group of distinct queries, so dashboards can attribute convoy wait
# vs group-formation wait separately
PHASE_QBATCH_GROUP = "qbatch_group_wait"
PHASE_STORAGE_READ = "storage_read"
PHASE_STAGING = "staging"
# staging split by outcome (ROADMAP item 1 attribution): an upload that
# actually moved column bytes vs a resident-store hit that moved none
PHASE_STAGING_UPLOAD = "staging_upload"
PHASE_STAGING_CACHE_HIT = "staging_cache_hit"
PHASE_COMPILE = "compile"
PHASE_EXECUTE = "execute"
PHASE_TOPK_MERGE = "topk_merge"
PHASE_ROOT_MERGE = "root_merge"
PHASE_FETCH_DOCS = "fetch_docs"
PHASE_LEAF_SEARCH = "leaf_search"


class QueryProfile:
    """Thread-safe per-query phase timeline + counters.

    Phases are recorded as dicts `{"name", "start_ms", "duration_ms",
    ...attrs}` with `start_ms` relative to profile creation; concurrent
    phases (fan-out threads, pool workers) simply overlap on the timeline.
    A phase aborted by an exception (deadline shed, injected fault) is
    STILL recorded, with its real partial duration and `"aborted": true` —
    profiles of shed queries must report partial phases, never zeros.
    """

    __slots__ = ("query_id", "created_at", "wall_ms", "partial",
                 "_phases", "_counters", "_children", "_lock")

    def __init__(self, query_id: str = ""):
        self.query_id = query_id
        self.created_at = time.monotonic()
        self.wall_ms: Optional[float] = None
        # set when the query was shed / timed out mid-flight: the waterfall
        # below it is truthful-but-incomplete
        self.partial: Optional[str] = None
        self._phases: list[dict[str, Any]] = []
        self._counters: dict[str, float] = {}
        # profiles returned by REMOTE leaves over the wire (embedded leaves
        # write into this profile directly through the ambient binding)
        self._children: list[dict[str, Any]] = []
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()

    # --- recording ---------------------------------------------------------
    @contextmanager
    def phase(self, name: str, **attrs: Any):
        """Time one phase; opens a `phase.<name>` span on the tracer so the
        waterfall stitches into OTLP. Yields the mutable record so callers
        can attach result attributes (bytes, cache hit, threshold, ...)."""
        from .tracing import TRACER
        start = time.monotonic()
        record: dict[str, Any] = dict(attrs)
        record["name"] = name
        record["start_ms"] = round((start - self.created_at) * 1000.0, 3)
        try:
            with TRACER.span(f"phase.{name}"):
                yield record
        except BaseException:
            record["aborted"] = True
            raise
        finally:
            elapsed = time.monotonic() - start
            record["duration_ms"] = round(elapsed * 1000.0, 3)
            with self._lock:
                self._phases.append(record)
            SEARCH_PHASE_SECONDS.observe(elapsed, phase=name)

    def record_phase(self, name: str, duration_secs: float,
                     start: Optional[float] = None, **attrs: Any) -> None:
        """Record an already-measured phase (for waits timed inside
        third-party blocking calls, e.g. the batcher follower wait)."""
        record: dict[str, Any] = dict(attrs)
        record["name"] = name
        origin = start if start is not None \
            else time.monotonic() - duration_secs
        record["start_ms"] = round((origin - self.created_at) * 1000.0, 3)
        record["duration_ms"] = round(duration_secs * 1000.0, 3)
        with self._lock:
            self._phases.append(record)
        SEARCH_PHASE_SECONDS.observe(duration_secs, phase=name)

    def add(self, counter: str, amount: float = 1.0) -> None:
        with self._lock:
            self._counters[counter] = self._counters.get(counter, 0.0) + amount

    def set_counter(self, counter: str, value: float) -> None:
        with self._lock:
            self._counters[counter] = value

    def mark_partial(self, reason: str) -> None:
        """First shed/timeout reason wins; later sheds keep the original."""
        with self._lock:
            if self.partial is None:
                self.partial = reason

    def add_child(self, child: dict[str, Any]) -> None:
        """Attach a remote leaf's serialized profile (arrived on the wire).
        Its phase durations roll up into this profile's histogram-free
        waterfall via `to_dict(...)["leaves"]`."""
        if child:
            with self._lock:
                self._children.append(child)

    def finish(self, wall_secs: Optional[float] = None) -> None:
        elapsed = wall_secs if wall_secs is not None \
            else time.monotonic() - self.created_at
        self.wall_ms = round(elapsed * 1000.0, 3)

    # --- views -------------------------------------------------------------
    def phases(self) -> list[dict[str, Any]]:
        with self._lock:
            return sorted((dict(p) for p in self._phases),
                          key=lambda p: p["start_ms"])

    def counters(self) -> dict[str, float]:
        with self._lock:
            return dict(self._counters)

    def phase_ms(self, name: str) -> float:
        """Total milliseconds recorded under `name` (all occurrences)."""
        with self._lock:
            return sum(p.get("duration_ms", 0.0) for p in self._phases
                       if p["name"] == name)

    def phase_ms_recursive(self, name: str) -> float:
        """Total milliseconds under `name` including remote leaves' child
        profiles — the cross-node attribution tenancy accounting charges
        (an embedded leaf writes into this profile directly, a remote one
        arrives as a child)."""
        def from_child(child: dict) -> float:
            total = sum(p.get("duration_ms", 0.0)
                        for p in child.get("phases", ())
                        if p.get("name") == name)
            return total + sum(from_child(c)
                               for c in child.get("leaves", ()))
        with self._lock:
            own = sum(p.get("duration_ms", 0.0) for p in self._phases
                      if p["name"] == name)
            children = [dict(c) for c in self._children]
        return own + sum(from_child(c) for c in children)

    def to_dict(self) -> dict[str, Any]:
        with self._lock:
            phases = sorted((dict(p) for p in self._phases),
                            key=lambda p: p["start_ms"])
            counters = dict(self._counters)
            children = [dict(c) for c in self._children]
        out: dict[str, Any] = {"phases": phases, "counters": counters}
        if self.query_id:
            out["query_id"] = self.query_id
        if self.wall_ms is not None:
            out["wall_ms"] = self.wall_ms
        if self.partial is not None:
            out["partial"] = self.partial
        if children:
            out["leaves"] = children
        return out


# --- ambient propagation (mirrors common/deadline.py) ----------------------

_CURRENT_PROFILE: contextvars.ContextVar[Optional[QueryProfile]] = (
    contextvars.ContextVar("quickwit_tpu_profile", default=None))


def current_profile() -> Optional[QueryProfile]:
    """The profile bound to this thread of execution, if any."""
    return _CURRENT_PROFILE.get()


@contextmanager
def profile_scope(profile: Optional[QueryProfile]):
    token = _CURRENT_PROFILE.set(profile)
    try:
        yield profile
    finally:
        _CURRENT_PROFILE.reset(token)


def bind_profile(fn: Callable, profile: Optional[QueryProfile] = None,
                 ) -> Callable:
    """Wrap `fn` so it runs under `profile` (default: the caller's current
    profile). Needed for ThreadPoolExecutor hops — contextvars do not
    propagate into pool worker threads automatically. When the captured
    profile is None the wrapper still rebinds None, which is free."""
    captured = profile if profile is not None else current_profile()

    def wrapper(*args, **kwargs):
        with profile_scope(captured):
            return fn(*args, **kwargs)

    return wrapper


class _NullPhase:
    """Reusable no-op context manager: the profiling-off path allocates
    nothing per call (acceptance: profile disabled adds no measurable
    per-query allocation on the hot path)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


def profiled_phase(name: str):
    """`with profiled_phase("staging") as rec:` — times the block into the
    ambient profile, or is a shared no-op when no profile is bound. `rec`
    is the mutable phase record (None when profiling is off)."""
    profile = _CURRENT_PROFILE.get()
    if profile is None:
        return _NULL_PHASE
    return profile.phase(name)


def profile_add(counter: str, amount: float = 1.0) -> None:
    """Bump a counter on the ambient profile; no-op (one ContextVar get)
    when profiling is off."""
    profile = _CURRENT_PROFILE.get()
    if profile is not None:
        profile.add(counter, amount)
