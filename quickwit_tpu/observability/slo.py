"""Per-tenant SLO burn accounting over the flight-recorder event stream.

Role of the reference's Datadog-side SLO monitors: each priority class
(`tenancy/context.py PRIORITY_CLASSES`) carries a latency objective and a
success-ratio target; every root query completion (the `query.done`
flight event's site in `search/root.py`) is judged against its class —
a breach is a shed/timed-out/errored query or a successful one over the
latency objective. Burn rate is the classic multiwindow quantity reduced
to one window: `breach_fraction / error_budget` over a sliding bucketed
window, so burn == 1.0 means the class is spending its budget exactly as
fast as the objective allows, and an alerting rule on
`qw_slo_burn_rate > N` needs no PromQL gymnastics.

Time comes from the clock seam (QW006-scoped): under DST the window
arithmetic runs on virtual time and is deterministic; in production the
seam is the real clock. Per-tenant attribution reuses the laundered
metric labels from `TenancyRegistry.metric_label` — the caller passes the
label so this module stays import-light (no tenancy dependency).
"""

from __future__ import annotations

from typing import Any, Optional

from ..common import sync
from ..common.clock import monotonic
from .metrics import SLO_BURN_RATE, SLO_OBJECTIVE_LATENCY_MS, SLO_QUERIES_TOTAL

# class -> (latency objective ms, success-ratio target). The error budget
# is 1 - target: interactive tenants get a tight objective and a thin
# budget, background a loose objective and a thick one.
DEFAULT_OBJECTIVES: dict[str, tuple[float, float]] = {
    "interactive": (500.0, 0.999),
    "standard": (2000.0, 0.99),
    "background": (10000.0, 0.95),
}

_BUCKET_SECS = 10.0
_WINDOW_BUCKETS = 30          # 5-minute sliding window


class SloTracker:
    """Windowed per-class breach accounting + cumulative per-tenant
    counters, mirrored into the `qw_slo_*` metric families."""

    def __init__(self,
                 objectives: Optional[dict[str, tuple[float, float]]] = None):
        self._lock = sync.lock("SloTracker._lock")
        self.configure(objectives)

    def configure(self,
                  objectives: Optional[dict[str, tuple[float, float]]] = None
                  ) -> None:
        with self._lock:
            self._objectives = dict(objectives or DEFAULT_OBJECTIVES)
            # class -> {bucket_index: [total, breached]} sliding window
            self._window: dict[str, dict[int, list[float]]] = {}
            # (tenant_label, class) -> [total, breached] cumulative
            self._tenants: dict[tuple[str, str], list[float]] = {}
        for cls, (latency_ms, _target) in self._objectives.items():
            SLO_OBJECTIVE_LATENCY_MS.set(latency_ms, priority_class=cls)

    def objective(self, priority_class: str) -> tuple[float, float]:
        with self._lock:
            return self._objectives.get(
                priority_class,
                self._objectives.get("standard", (2000.0, 0.99)))

    # ------------------------------------------------------------------
    def note(self, priority_class: str, tenant_label: str,
             latency_ms: float, ok: bool) -> float:
        """Judge one completed query; returns the class's current burn
        rate. `ok=False` (shed / timed out / errored) is always a breach;
        an ok query breaches when it blew the latency objective."""
        latency_objective_ms, target = self.objective(priority_class)
        breach = (not ok) or latency_ms > latency_objective_ms
        budget = max(1.0 - target, 1e-6)
        bucket = int(monotonic() // _BUCKET_SECS)
        with self._lock:
            window = self._window.setdefault(priority_class, {})
            cell = window.setdefault(bucket, [0.0, 0.0])
            cell[0] += 1.0
            if breach:
                cell[1] += 1.0
            # expire buckets that slid out of the window
            floor = bucket - _WINDOW_BUCKETS
            for b in [b for b in window if b <= floor]:
                del window[b]
            total = sum(c[0] for c in window.values())
            breached = sum(c[1] for c in window.values())
            tcell = self._tenants.setdefault(
                (tenant_label, priority_class), [0.0, 0.0])
            tcell[0] += 1.0
            if breach:
                tcell[1] += 1.0
        burn = (breached / total) / budget if total else 0.0
        SLO_QUERIES_TOTAL.inc(priority_class=priority_class,
                              verdict="breach" if breach else "ok",
                              tenant=tenant_label)
        SLO_BURN_RATE.set(round(burn, 6), priority_class=priority_class)
        return burn

    # ------------------------------------------------------------------
    def report(self) -> dict[str, Any]:
        """JSON snapshot for the developer endpoint: objectives, live
        windowed burn per class, cumulative per-tenant breach counts."""
        with self._lock:
            objectives = dict(self._objectives)
            window = {cls: {b: list(c) for b, c in w.items()}
                      for cls, w in self._window.items()}
            tenants = {k: list(v) for k, v in self._tenants.items()}
        classes: dict[str, Any] = {}
        for cls, (latency_ms, target) in sorted(objectives.items()):
            w = window.get(cls, {})
            total = sum(c[0] for c in w.values())
            breached = sum(c[1] for c in w.values())
            budget = max(1.0 - target, 1e-6)
            classes[cls] = {
                "latency_objective_ms": latency_ms,
                "success_target": target,
                "window_secs": _BUCKET_SECS * _WINDOW_BUCKETS,
                "window_total": total,
                "window_breached": breached,
                "burn_rate": round((breached / total) / budget, 6)
                if total else 0.0,
            }
        per_tenant: dict[str, Any] = {}
        for (label, cls), (total, breached) in sorted(tenants.items()):
            per_tenant.setdefault(label, {})[cls] = {
                "total": total, "breached": breached}
        return {"classes": classes, "tenants": per_tenant}

    def reset(self) -> None:
        """Drop observations, keep objectives — test isolation."""
        with self._lock:
            self._window.clear()
            self._tenants.clear()


# Process-global tracker, matching METRICS / FLIGHT / OVERLOAD: the root
# searcher feeds it, the developer endpoint reports it.
SLO_TRACKER = SloTracker()
