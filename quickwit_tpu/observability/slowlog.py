"""Ring-buffer slow-query log.

Role of the reference's slow-request logging (`quickwit-serve` request
logging + `rate_limited_tracing`): queries whose wall time exceeds a
configurable threshold — or that were shed / timed out — retain their full
execution profile in a bounded in-memory ring buffer, inspectable at
`/api/v1/developer/slowlog` and dumped by the soak test. FIFO eviction:
the buffer holds the most recent `capacity` slow queries.

Arming: the threshold comes from the constructor or the
`QW_SLOWLOG_THRESHOLD_MS` environment variable. While armed, the root
searcher profiles EVERY query (the profile is cheap; capture must not
require re-running the slow query with `"profile": true`). Unarmed — the
default — nothing is allocated.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Optional

from .metrics import SEARCH_SLOWLOG_RECORDED_TOTAL


def _env_threshold_ms() -> Optional[float]:
    raw = os.environ.get("QW_SLOWLOG_THRESHOLD_MS")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


class SlowQueryLog:
    """Thread-safe FIFO ring buffer of slow-query profile entries."""

    def __init__(self, capacity: int = 64,
                 threshold_ms: Optional[float] = None):
        self.capacity = capacity
        self._threshold_ms = threshold_ms
        self._entries: deque[dict[str, Any]] = deque(maxlen=capacity)
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()

    @property
    def threshold_ms(self) -> Optional[float]:
        return self._threshold_ms if self._threshold_ms is not None \
            else _env_threshold_ms()

    def configure(self, threshold_ms: Optional[float]) -> None:
        self._threshold_ms = threshold_ms

    @property
    def armed(self) -> bool:
        return self.threshold_ms is not None

    def should_capture(self, elapsed_ms: float, timed_out: bool) -> bool:
        """Shed/timed-out queries are always slowlog-worthy when armed —
        they are the queries whose waterfall matters most."""
        threshold = self.threshold_ms
        if threshold is None:
            return False
        return timed_out or elapsed_ms >= threshold

    def record(self, entry: dict[str, Any]) -> None:
        entry = dict(entry)
        entry.setdefault("recorded_at", time.time())
        # automatic flight-recorder tail capture: the slow query's device
        # timeline (group formation, staging, dispatch, chunk boundaries,
        # readback) rides along with its profile waterfall
        query_id = entry.get("query_id")
        if query_id and "flight" not in entry:
            from .flight import FLIGHT
            entry["flight"] = FLIGHT.tail_for_query(query_id)
        with self._lock:
            self._entries.append(entry)
        SEARCH_SLOWLOG_RECORDED_TOTAL.inc()

    def entries(self) -> list[dict[str, Any]]:
        """Oldest → newest (deque evicts from the left when full)."""
        with self._lock:
            return [dict(e) for e in self._entries]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# Process-global instance: REST endpoint, root searcher and tests share it
# (per-node isolation is by query_id / index attribution, matching the
# process-global TRACER and METRICS).
SLOW_QUERY_LOG = SlowQueryLog()
