"""Self-tracing: spans on the engine's own hot paths, exported as OTLP.

Role of the reference's `tracing` + `tracing-opentelemetry` setup and the
`quickwit-telemetry-exporters` crate (`quickwit-common/src/
tracing_utils.rs:23-112` for W3C context propagation,
`rate_limited_tracing.rs:306` for log rate limiting): the engine traces
its own request handling and can ship those spans to any OTLP consumer —
including ITSELF (the node's own otel-traces index), closing the
"quickwit observes quickwit" loop.

Design: a tiny thread-local tracer (no external dependency), W3C
`traceparent` inject/extract so spans stitch across the root↔leaf HTTP
hop, and a batch exporter that renders finished spans as OTLP JSON
`resourceSpans`. Export re-entrancy is suppressed: spans opened while an
export is in flight are dropped, not queued, so exporting into the local
otel index cannot trace itself into a feedback loop.
"""

from __future__ import annotations

import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Optional


@dataclass
class SpanData:
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars
    parent_span_id: str
    name: str
    start_ns: int = 0
    end_ns: int = 0
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "unset"
    # which node produced the span: set by the server entry point and
    # inherited by children, so per-node exporters on the process-global
    # tracer only ship their own node's spans (multi-node-per-process
    # tests and in-process clusters)
    scope: str = ""

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value


def _new_id(nbytes: int) -> str:
    # os.urandom, not the global PRNG: seeded harnesses and forked workers
    # share `random` state and would mint colliding trace/span ids
    return os.urandom(nbytes).hex()


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: str) -> Optional[tuple[str, str]]:
    """W3C traceparent: version-traceid-spanid-flags. Returns
    (trace_id, span_id) or None on malformed/all-zero input."""
    parts = (header or "").strip().split("-")
    if len(parts) < 4 or parts[0] == "ff":
        return None
    trace_id, span_id = parts[1].lower(), parts[2].lower()
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    if set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return trace_id, span_id


class Tracer:
    """Thread-local span stack + fan-out to processors on span end."""

    def __init__(self) -> None:
        self._tls = threading.local()
        self._processors: list[Callable[[SpanData], None]] = []
        self.enabled = True

    # --- processors --------------------------------------------------------
    def add_processor(self, processor: Callable[[SpanData], None]) -> None:
        self._processors.append(processor)

    def remove_processor(self, processor) -> None:
        if processor in self._processors:
            self._processors.remove(processor)

    # --- context -----------------------------------------------------------
    def _stack(self) -> list[SpanData]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_span(self) -> Optional[SpanData]:
        stack = self._stack()
        return stack[-1] if stack else None

    def current_traceparent(self) -> Optional[str]:
        span = self.current_span()
        if span is None:
            return None
        return format_traceparent(span.trace_id, span.span_id)

    @property
    def _suppressed(self) -> bool:
        return getattr(self._tls, "suppress", False)

    @contextmanager
    def suppress(self):
        """No spans recorded inside (export paths: no feedback loops)."""
        prev = self._suppressed
        self._tls.suppress = True
        try:
            yield
        finally:
            self._tls.suppress = prev

    # --- spans -------------------------------------------------------------
    @contextmanager
    def span(self, name: str, attributes: Optional[dict[str, Any]] = None,
             remote_parent: Optional[str] = None, scope: str = ""):
        """Span context manager. `remote_parent` is an incoming W3C
        traceparent header; when valid, the span joins that trace.
        `scope` tags the span's producer (node id); children inherit."""
        if not self.enabled or self._suppressed:
            yield SpanData("", "", "", name)
            return
        stack = self._stack()
        parent = stack[-1] if stack else None
        trace_id = parent.trace_id if parent else None
        parent_id = parent.span_id if parent else ""
        if parent is None and remote_parent:
            remote = parse_traceparent(remote_parent)
            if remote is not None:
                trace_id, parent_id = remote
        span = SpanData(
            trace_id=trace_id or _new_id(16),
            span_id=_new_id(8),
            parent_span_id=parent_id,
            name=name,
            start_ns=time.time_ns(),
            attributes=dict(attributes or {}),
            scope=scope or (parent.scope if parent else ""))
        stack.append(span)
        try:
            yield span
            if span.status == "unset":
                span.status = "ok"
        except BaseException:
            # a handler that already classified the failure (e.g. a REST
            # 4xx mapped below the span) keeps its classification
            if span.status == "unset":
                span.status = "error"
            raise
        finally:
            span.end_ns = time.time_ns()
            stack.pop()
            for processor in self._processors:
                try:
                    processor(span)
                except Exception:  # noqa: BLE001 - never break the traced path
                    pass


TRACER = Tracer()


def spans_to_otlp(spans: list[SpanData], service_name: str,
                  node_id: str = "") -> dict[str, Any]:
    """Finished spans → OTLP JSON `resourceSpans` (the shape both our
    `/otlp/v1/traces` endpoint and any OTLP collector accept)."""
    def _attrs(mapping: dict[str, Any]) -> list[dict[str, Any]]:
        out = []
        for key, value in mapping.items():
            if isinstance(value, bool):
                v: dict[str, Any] = {"boolValue": value}
            elif isinstance(value, int):
                v = {"intValue": str(value)}
            elif isinstance(value, float):
                v = {"doubleValue": value}
            else:
                v = {"stringValue": str(value)}
            out.append({"key": key, "value": v})
        return out

    resource_attrs = {"service.name": service_name}
    if node_id:
        resource_attrs["node.id"] = node_id
    return {"resourceSpans": [{
        "resource": {"attributes": _attrs(resource_attrs)},
        "scopeSpans": [{
            "scope": {"name": "quickwit_tpu.self_tracing"},
            "spans": [{
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "parentSpanId": s.parent_span_id,
                "name": s.name,
                "startTimeUnixNano": str(s.start_ns),
                "endTimeUnixNano": str(s.end_ns),
                # proto3 JSON enum name (a real otel-collector rejects
                # bare lowercase strings)
                "status": {"code": {"ok": "STATUS_CODE_OK",
                                    "error": "STATUS_CODE_ERROR"}.get(
                                        s.status, "STATUS_CODE_UNSET")},
                "attributes": _attrs(s.attributes),
            } for s in spans],
        }],
    }]}


class BatchSpanExporter:
    """Span processor that batches and ships (reference: the OTLP span
    exporter installed by quickwit-telemetry-exporters). `export_fn`
    receives an OTLP JSON payload; failures drop the batch (telemetry is
    best-effort and must never apply backpressure to the data path)."""

    def __init__(self, export_fn: Callable[[dict[str, Any]], None],
                 service_name: str = "quickwit-tpu", node_id: str = "",
                 max_batch: int = 256, interval_secs: float = 5.0,
                 max_buffer: int = 4096, scope: str = ""):
        self.export_fn = export_fn
        self.service_name = service_name
        self.node_id = node_id
        # only ship spans tagged with this producer scope ("" = all):
        # several self-tracing nodes in one process each export exactly
        # their own spans, correctly attributed
        self.scope = scope
        self.max_batch = max_batch
        self.interval_secs = interval_secs
        self.max_buffer = max_buffer
        self._buffer: list[SpanData] = []
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._wake = threading.Event()
        self._stop = False
        # qwlint: disable-next-line=QW003 - exporter drains finished spans
        # for ALL queries; binding one query's context would be wrong
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._thread = threading.Thread(target=self._run,
                                        name="span-exporter", daemon=True)
        self._thread.start()

    def __call__(self, span: SpanData) -> None:  # Tracer processor hook
        if self.scope and span.scope != self.scope:
            return
        with self._lock:
            if len(self._buffer) >= self.max_buffer:
                return  # shed, never block the traced path
            self._buffer.append(span)
            full = len(self._buffer) >= self.max_batch
        if full:
            self._wake.set()

    def _drain(self) -> list[SpanData]:
        with self._lock:
            batch, self._buffer = self._buffer, []
        return batch

    def _export(self, batch: list[SpanData]) -> None:
        if not batch:
            return
        payload = spans_to_otlp(batch, self.service_name, self.node_id)
        with TRACER.suppress():
            try:
                self.export_fn(payload)
            except Exception:  # noqa: BLE001 - telemetry is best-effort
                pass

    def _run(self) -> None:
        while not self._stop:
            self._wake.wait(timeout=self.interval_secs)
            self._wake.clear()
            self._export(self._drain())

    def flush(self) -> None:
        self._export(self._drain())

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=2.0)
        self.flush()


class RateLimitedLog:
    """`rate_limited_tracing.rs` analogue: at most `limit` emissions of a
    keyed message per `period_secs` window; excess calls are counted and
    the count is reported on the window's first emission after reset."""

    def __init__(self, limit: int = 5, period_secs: float = 60.0,
                 clock=time.monotonic):
        self.limit = limit
        self.period_secs = period_secs
        self.clock = clock
        # qwlint: disable-next-line=QW008 - metrics/tracing leaf locks; counter
        # updates only, no instrumented ops inside
        self._lock = threading.Lock()
        self._windows: dict[str, tuple[float, int, int]] = {}

    def should_log(self, key: str) -> tuple[bool, int]:
        """(emit_now, num_suppressed_since_last_emit)."""
        now = self.clock()
        with self._lock:
            start, emitted, suppressed = self._windows.get(key,
                                                           (now, 0, 0))
            if now - start >= self.period_secs:
                start, emitted, suppressed = now, 0, suppressed
            if emitted < self.limit:
                self._windows[key] = (start, emitted + 1, 0)
                return True, suppressed
            self._windows[key] = (start, emitted, suppressed + 1)
            return False, 0
