from .metrics import Counter, Gauge, Histogram, MetricsRegistry, METRICS

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "METRICS"]
