"""Flight recorder: the always-on device-timeline black box.

Role of the reference's Datadog-side continuous telemetry (the fork's
runtime emits its own traces/metrics into the platform that hosts it):
between the per-query profile waterfall (`observability/profile.py`) and
the aggregate Prometheus counters there was no way to reconstruct *what
the device and its queues were doing* around an incident. The flight
recorder closes that gap: every hot subsystem emits typed lifecycle
events — batcher group formation/shedding, staging uploads vs resident
hits, compile-cache hit/miss, dispatch launch/readback, chunk boundaries
and preemption park/evict/resume, mesh collective phases, cache-tier
hit/fill/evict, DRR admission grants, overload-ladder transitions,
cancellation — into per-thread ring buffers that are always recording
and bounded in both memory and overhead.

Design constraints, all load-bearing:

- **Per-thread rings, lock-free appends.** Each thread owns a fixed-size
  ring (`threading.local` lookup + list slot store); the only lock is the
  registry lock taken once per thread lifetime (constructed through the
  `common/sync.py` seam). Overwrite-oldest semantics: a storm costs
  events, never memory or blocking.
- **Clock seam.** Timestamps come from `common/clock.monotonic()` (this
  module is qwlint QW006-scoped), so under the DST harness a recording is
  a pure function of the run: virtual time in, byte-identical timeline
  out. `dst_tail()` exports only the *calling thread's* ring — the DST op
  thread — so the embedded artifact timeline is deterministic by
  construction even when worker pools race.
- **Zero allocation when disabled.** `QW_DISABLE_FLIGHT=1` (or
  `FLIGHT.disable()`) makes `emit()` a single attribute check and return;
  no tuples, dicts or label lookups are built. Call sites that must
  *compute* attributes guard with `FLIGHT.recording()` first, mirroring
  the `_NULL_PHASE` pattern in `profile.py`.
- **Attribution for free.** When `query_id`/`tenant` are not passed,
  `emit()` reads the ambient `QueryProfile` and `TenantContext`
  contextvars (one get each) so every event in a query's flow correlates
  without threading ids through signatures; an active OTLP span's
  traceparent is captured for span correlation in the Chrome export.

Exports: `to_chrome_trace()` renders the merged timeline as Chrome
trace-event / Perfetto JSON (`GET /api/v1/developer/trace`, `python -m
quickwit_tpu.cli trace export`); `tail_for_query()` attaches a query's
events to its slowlog entry; `dst_tail()` feeds DST violation artifacts.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from ..common import sync
from ..common.clock import monotonic
from .metrics import (
    FLIGHT_DROPPED_EVENTS, FLIGHT_EVENTS_TOTAL, FLIGHT_EXPORTS_TOTAL,
    FLIGHT_THREADS,
)

DEFAULT_CAPACITY = int(os.environ.get("QW_FLIGHT_CAPACITY", 4096))


def _env_disabled() -> bool:
    return os.environ.get("QW_DISABLE_FLIGHT", "").strip().lower() \
        in ("1", "true", "yes")


# Ambient-context accessors, bound on first emit. They cannot be plain
# top-level imports (profile/tenancy/tracing would form an import cycle
# through the subsystems that import this module), but a `from x import y`
# *inside* emit() costs ~6us/call in importlib machinery — the hot path
# resolves them once and caches the callables here.
_HOT_BINDINGS: Optional[tuple] = None


def _hot_bindings() -> tuple:
    global _HOT_BINDINGS
    if _HOT_BINDINGS is None:
        from ..tenancy.context import current_tenant
        from .profile import current_profile
        from .tracing import TRACER
        _HOT_BINDINGS = (current_profile, current_tenant,
                         TRACER.current_traceparent)
    return _HOT_BINDINGS


class _Ring:
    """One thread's event ring. Appends are lock-free: only the owning
    thread writes, readers take a racy-but-safe snapshot (slots hold
    immutable tuples; a torn read can at worst miss/duplicate the event
    being written, acceptable for a diagnostic export)."""

    __slots__ = ("tid", "name", "capacity", "buf", "idx", "seq", "dropped",
                 "counts", "flushed")

    def __init__(self, tid: int, name: str, capacity: int):
        self.tid = tid            # logical id (registration order), not OS id
        self.name = name
        self.capacity = capacity
        self.buf: list = [None] * capacity
        self.idx = 0              # next write slot
        self.seq = 0              # events ever written (per-thread order)
        self.dropped = 0          # events overwritten by ring wrap
        # per-kind event counts, owner-thread writes only: the labeled
        # Prometheus counter costs a lock + label-key sort per inc, which
        # is too much for the emit hot path — counts accumulate here and
        # fold into FLIGHT_EVENTS_TOTAL at snapshot/scrape time
        self.counts: dict = {}
        self.flushed: dict = {}   # counts already folded into the metric

    def append(self, event: tuple) -> None:
        i = self.idx
        if self.buf[i] is not None:
            self.dropped += 1
        self.buf[i] = event
        self.idx = (i + 1) % self.capacity
        self.seq += 1

    def snapshot(self) -> list:
        """Events oldest -> newest (per-thread seq order)."""
        i, buf = self.idx, list(self.buf)
        ordered = [e for e in buf[i:] + buf[:i] if e is not None]
        return ordered

    def clear(self) -> None:
        self.buf = [None] * self.capacity
        self.idx = 0
        self.seq = 0
        self.dropped = 0
        # flushed resets with counts: the Prometheus counter is monotonic
        # (it keeps what was already folded in), deltas just restart at 0
        self.counts = {}
        self.flushed = {}


def _event_dict(event: tuple, tid: Optional[int] = None,
                with_span: bool = True) -> dict[str, Any]:
    seq, t_ms, kind, query_id, tenant, span, attrs = event
    out: dict[str, Any] = {"t_ms": t_ms, "kind": kind}
    if query_id:
        out["query_id"] = query_id
    if tenant:
        out["tenant"] = tenant
    if with_span and span:
        out["span"] = span
    if attrs:
        out["attrs"] = dict(attrs)
    if tid is not None:
        out["tid"] = tid
    return out


class FlightRecorder:
    """Process-global always-on event recorder (see module docstring)."""

    def __init__(self, capacity_per_thread: int = DEFAULT_CAPACITY):
        self.capacity = max(int(capacity_per_thread), 16)
        self._lock = sync.lock("FlightRecorder._lock")
        self._rings: list[_Ring] = []
        # threading.local is a plain TLS slot, not a QW008 primitive; the
        # per-thread ring lives here so emit() never takes the registry lock
        self._tl = threading.local()
        self._epoch = monotonic()
        self._enabled = not _env_disabled()

    # --- on/off -----------------------------------------------------------
    def recording(self) -> bool:
        return self._enabled

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    # --- recording --------------------------------------------------------
    def _ring(self) -> _Ring:
        ring = getattr(self._tl, "ring", None)
        if ring is None:
            with self._lock:
                ring = _Ring(len(self._rings) + 1,
                             threading.current_thread().name, self.capacity)
                self._rings.append(ring)
                FLIGHT_THREADS.set(float(len(self._rings)))
            self._tl.ring = ring
        return ring

    def emit(self, kind: str, query_id: str = "", tenant: str = "",
             attrs: Optional[dict] = None) -> None:
        """Record one typed event. `kind` is `"<subsystem>.<what>"` from a
        fixed vocabulary (greppable at the call sites). When `query_id` /
        `tenant` are empty they are resolved from the ambient profile and
        tenant contextvars. Disabled -> one attribute check, no allocation.
        """
        if not self._enabled:
            return
        current_profile, current_tenant, current_traceparent = \
            _HOT_BINDINGS or _hot_bindings()
        t_ms = round((monotonic() - self._epoch) * 1000.0, 3)
        if not query_id:
            profile = current_profile()
            if profile is not None:
                query_id = profile.query_id
        if not tenant:
            ctx = current_tenant()
            if ctx is not None:
                tenant = ctx.tenant_id
        span = current_traceparent()
        ring = self._ring()
        ring.append((ring.seq, t_ms, kind, query_id, tenant, span, attrs))
        counts = ring.counts
        counts[kind] = counts.get(kind, 0) + 1

    # --- run boundaries (DST) --------------------------------------------
    def begin_run(self) -> None:
        """Reset all rings and rebase the epoch on the *current* clock —
        the DST harness calls this after installing the FakeClock so every
        run's timeline starts at t=0 virtual and is a pure function of the
        run inputs."""
        with self._lock:
            for ring in self._rings:
                ring.clear()
            self._epoch = monotonic()

    reset = begin_run  # test-friendly alias

    # --- export -----------------------------------------------------------
    def _snapshot_rings(self) -> list[_Ring]:
        with self._lock:
            rings = list(self._rings)
            FLIGHT_DROPPED_EVENTS.set(float(sum(r.dropped for r in rings)))
            # fold per-ring event counts into the labeled Prometheus
            # counter (deltas only; the counter stays monotonic across
            # begin_run ring clears)
            for ring in rings:
                for kind, n in list(ring.counts.items()):
                    delta = n - ring.flushed.get(kind, 0)
                    if delta:
                        FLIGHT_EVENTS_TOTAL.inc(
                            delta, subsystem=kind.split(".", 1)[0])
                        ring.flushed[kind] = n
        return rings

    def flush_metrics(self) -> None:
        """Fold buffered event counts into `qw_flight_*` metrics. emit()
        never touches the labeled counter (lock + label-key sort per inc
        is too slow for the hot path); the /metrics scrape and every
        export path call this instead."""
        self._snapshot_rings()

    def events(self, limit: Optional[int] = None,
               with_span: bool = True) -> list[dict[str, Any]]:
        """Merged timeline across every thread, oldest -> newest, ordered
        by (t_ms, tid, per-thread seq)."""
        merged: list[tuple] = []
        for ring in self._snapshot_rings():
            merged.extend((e[1], ring.tid, e[0], e)
                          for e in ring.snapshot())
        merged.sort(key=lambda x: (x[0], x[1], x[2]))
        if limit is not None and len(merged) > limit:
            merged = merged[-limit:]
        return [_event_dict(e, tid=tid, with_span=with_span)
                for _, tid, _, e in merged]

    def tail_for_query(self, query_id: str,
                       limit: int = 96) -> list[dict[str, Any]]:
        """The most recent events attributed to `query_id`, merged across
        threads — attached to slowlog entries so a slow query carries the
        device timeline that produced it."""
        if not query_id:
            return []
        merged: list[tuple] = []
        for ring in self._snapshot_rings():
            merged.extend((e[1], ring.tid, e[0], e)
                          for e in ring.snapshot() if e[3] == query_id)
        merged.sort(key=lambda x: (x[0], x[1], x[2]))
        if len(merged) > limit:
            merged = merged[-limit:]
        return [_event_dict(e, tid=tid) for _, tid, _, e in merged]

    def dst_tail(self, limit: int = 256) -> list[dict[str, Any]]:
        """The calling thread's own timeline tail, stripped of every
        nondeterministic field (no OS/logical thread ids, no random span
        ids): under the DST harness this is byte-identical across replays
        of the same (scenario, seed, ops, fault plan). `compile.*` events
        are filtered: the JIT executable caches are per-process, so
        hit-vs-miss reflects what *earlier* runs compiled — true process
        state, but not a function of this run's inputs."""
        events = [e for e in self._ring().snapshot()
                  if not e[2].startswith("compile.")]
        if len(events) > limit:
            events = events[-limit:]
        return [_event_dict(e, with_span=False) for e in events]

    def to_chrome_trace(self, limit: Optional[int] = None,
                        process_name: str = "quickwit_tpu"
                        ) -> dict[str, Any]:
        """Chrome trace-event / Perfetto JSON: instant events (`ph: "i"`,
        thread-scoped) or complete events (`ph: "X"`) when the emitting
        site measured a duration (`attrs["dur_ms"]`), with query-id /
        tenant / traceparent correlation in `args`."""
        FLIGHT_EXPORTS_TOTAL.inc()
        rings = self._snapshot_rings()
        trace_events: list[dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
             "args": {"name": process_name}},
        ]
        for ring in rings:
            trace_events.append(
                {"name": "thread_name", "ph": "M", "pid": 1,
                 "tid": ring.tid, "args": {"name": ring.name}})
        merged: list[tuple] = []
        for ring in rings:
            merged.extend((e[1], ring.tid, e[0], e)
                          for e in ring.snapshot())
        merged.sort(key=lambda x: (x[0], x[1], x[2]))
        if limit is not None and len(merged) > limit:
            merged = merged[-limit:]
        for t_ms, tid, _seq, event in merged:
            _, _, kind, query_id, tenant, span, attrs = event
            args: dict[str, Any] = {}
            if query_id:
                args["query_id"] = query_id
            if tenant:
                args["tenant"] = tenant
            if span:
                args["traceparent"] = span
            if attrs:
                args.update(attrs)
            record: dict[str, Any] = {
                "name": kind, "cat": kind.split(".", 1)[0],
                "ts": int(round(t_ms * 1000.0)),   # microseconds
                "pid": 1, "tid": tid, "args": args,
            }
            dur_ms = attrs.get("dur_ms") if attrs else None
            if dur_ms is not None:
                record["ph"] = "X"
                record["dur"] = max(int(round(float(dur_ms) * 1000.0)), 1)
            else:
                record["ph"] = "i"
                record["s"] = "t"
            trace_events.append(record)
        return {"traceEvents": trace_events, "displayTimeUnit": "ms",
                "metadata": {"recorder": "quickwit_tpu.flight",
                             "dropped_events":
                                 sum(r.dropped for r in rings)}}

    def stats(self) -> dict[str, Any]:
        rings = self._snapshot_rings()
        return {"enabled": self._enabled,
                "capacity_per_thread": self.capacity,
                "threads": len(rings),
                "events": sum(min(r.seq, r.capacity) for r in rings),
                "dropped": sum(r.dropped for r in rings)}


# Process-global recorder, matching METRICS / SLOW_QUERY_LOG / OVERLOAD:
# every subsystem emits into it, the REST/CLI exporters read from it.
FLIGHT = FlightRecorder()


# Module-level shorthand for `FLIGHT.emit` (the hot-path spelling): the
# bound method directly, so an emit costs one call frame, and a disabled
# emit is that frame plus a single attribute check.
emit = FLIGHT.emit


def recording() -> bool:
    """True when emitting records. Sites that must allocate attrs dicts
    guard with this so the disabled path stays allocation-free."""
    return FLIGHT._enabled
