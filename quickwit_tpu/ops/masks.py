"""Dense doc-set masks — the TPU replacement for posting-list iteration.

The reference's leaf loop (tantivy posting decode → boolean
intersection/union, SURVEY.md §3.2 hot box) walks compressed posting lists
with scalar cursors. On TPU the doc set of a split is a **dense bool vector**
of length `num_docs_padded`: term postings scatter into it, boolean operators
are elementwise VPU ops, ranges are vectorized compares on resident columns.
Everything here is shape-static and jit-safe.

Padding convention (see index/writer.py): posting pad slots carry
`doc_id == num_docs_padded` (out of bounds → dropped by scatter `mode="drop"`)
and `tf == 0`.
"""

from __future__ import annotations

import jax.numpy as jnp

# qwir R5 anchor: the per-doc live-lane byte budget of the leaf kernel.
# Dense doc-space intermediates are minted here (bool masks, scatter
# targets) and in executor._keyed_for (f64 sort keys): at any point the
# kernel holds at most ~8 doc-scale lanes live — predicate masks under a
# bool combine, the valid-docs mask, f32 scores, two f64 sort keys, the
# i32 doc key, and a zonemap-blocked compare temp — ≈ 40 bytes/doc, with
# headroom for XLA keeping a few extra temps unfused. tools/qwir's
# buffer-liveness walk (rule R5) enforces
#     peak_bytes <= inputs + QWIR_PEAK_PER_DOC_BYTES * doc_lanes + fixed
# per audited program: a change that starts materializing O(docs) state
# beyond this budget (e.g. a [docs, docs] pairwise temp, or per-doc
# bucket replication) fails the audit instead of silently eating HBM that
# admission (search/admission.py::HbmBudget) never accounted.
QWIR_PEAK_PER_DOC_BYTES = 96


def mask_from_postings(doc_ids: jnp.ndarray, num_docs_padded: int) -> jnp.ndarray:
    """Presence mask from a (padded) posting id array."""
    mask = jnp.zeros(num_docs_padded, dtype=jnp.bool_)
    return mask.at[doc_ids].set(True, mode="drop")


def dense_from_postings(doc_ids: jnp.ndarray, values: jnp.ndarray,
                        num_docs_padded: int, dtype=jnp.float32) -> jnp.ndarray:
    """Scatter per-posting values (tf, scores) into a dense per-doc array."""
    dense = jnp.zeros(num_docs_padded, dtype=dtype)
    return dense.at[doc_ids].add(values.astype(dtype), mode="drop")


def valid_docs_mask(num_docs: jnp.ndarray, num_docs_padded: int) -> jnp.ndarray:
    """True for real docs, False for the pad tail."""
    return jnp.arange(num_docs_padded, dtype=jnp.int32) < num_docs


def and_masks(*ms: jnp.ndarray) -> jnp.ndarray:
    out = ms[0]
    for m in ms[1:]:
        out = out & m
    return out


def or_masks(*ms: jnp.ndarray) -> jnp.ndarray:
    out = ms[0]
    for m in ms[1:]:
        out = out | m
    return out


def not_mask(m: jnp.ndarray) -> jnp.ndarray:
    return ~m


def range_mask(values: jnp.ndarray, present: jnp.ndarray,
               lower, upper, lower_incl: bool, upper_incl: bool,
               has_lower: bool, has_upper: bool,
               zmin: jnp.ndarray = None, zmax: jnp.ndarray = None,
               zonemap_block: int = 512) -> jnp.ndarray:
    """Range predicate over a numeric fast column.

    `has_*`/`*_incl` are static (they shape the compiled graph); the bounds
    themselves are traced scalars so the same compiled plan serves different
    bound values.

    Block-sparse evaluation: when per-block zonemaps (`zmin`/`zmax`, one
    entry per `zonemap_block` doc lanes, same domain as `values` — scaled
    deltas for FOR-packed columns) ride along as traced operands, the
    per-doc compare is gated by a block-level prequalification mask: a
    block whose [zmin, zmax] envelope cannot intersect the bounds
    contributes no lanes, mirroring split-level pruning
    (search/pruning.py) one level down. Blocks with no present docs carry
    inverted sentinels and never qualify.
    """
    if zmin is not None:
        blk_ok = jnp.ones(zmin.shape, dtype=jnp.bool_)
        if has_lower:
            blk_ok = blk_ok & (zmax >= lower if lower_incl else zmax > lower)
        if has_upper:
            blk_ok = blk_ok & (zmin <= upper if upper_incl else zmin < upper)
        nb = zmin.shape[0]
        blocked = values.reshape(nb, zonemap_block)
        pblocked = present.reshape(nb, zonemap_block).astype(jnp.bool_)
        mask = jnp.where(blk_ok[:, None], pblocked, False)
        if has_lower:
            mask = mask & jnp.where(
                blk_ok[:, None],
                blocked >= lower if lower_incl else blocked > lower, False)
        if has_upper:
            mask = mask & jnp.where(
                blk_ok[:, None],
                blocked <= upper if upper_incl else blocked < upper, False)
        return mask.reshape(-1)
    mask = present.astype(jnp.bool_)
    if has_lower:
        mask = mask & (values >= lower if lower_incl else values > lower)
    if has_upper:
        mask = mask & (values <= upper if upper_incl else values < upper)
    return mask


def minimum_should_match_mask(should_masks: list[jnp.ndarray], min_count: int) -> jnp.ndarray:
    """At least `min_count` of the masks true (bool `should` semantics)."""
    counts = sum(m.astype(jnp.int32) for m in should_masks)
    return counts >= min_count


def dead_lane_mask(keyed: jnp.ndarray) -> jnp.ndarray:
    """Lanes whose higher-is-better sort key is -inf: non-matching docs,
    threshold-pruned lanes, and search_after-excluded lanes. These never
    surface through top-k, and the scalar-only readback's hit lists are
    meaningless past the live prefix."""
    return jnp.isneginf(keyed)


def propagate_dead_lanes(keyed: jnp.ndarray,
                         keyed2: jnp.ndarray) -> jnp.ndarray:
    """Kill the secondary sort key wherever the primary lane is dead, so
    the lexicographic 2-key top-k cannot resurrect a pruned/excluded doc
    on the strength of its tiebreaker alone."""
    return jnp.where(dead_lane_mask(keyed), -jnp.inf, keyed2)
