"""Host-side phrase matching over positional postings.

Role of tantivy's `PhraseScorer` in the reference's leaf loop. Phrase
evaluation is a *pre-pass* in this engine: it runs on the (host-resident)
postings + positions of the phrase terms and produces a precomputed posting
list (doc ids + phrase frequencies) that enters the device plan like any
term's postings. This keeps the device graph static while supporting exact
phrases; a Pallas positional kernel is the planned upgrade path.

slop>0 uses the k-way minimal-window algorithm over RELATIVE positions
(p_i - i): an alignment of the phrase terms matches when the spread of
their relative positions is <= slop — tantivy's PhraseScorer semantics.
"""

from __future__ import annotations

import numpy as np


def phrase_match(
    postings: list[tuple[np.ndarray, np.ndarray]],
    positions: list[tuple[np.ndarray, np.ndarray]],
    dfs: list[int],
    slop: int = 0,
    term_keys: list | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Docs containing the terms as an exact phrase.

    `postings[i] = (padded_ids, padded_tfs)` and
    `positions[i] = (offsets[post_len+1], data)` for phrase term i, with
    `dfs[i]` real (unpadded) postings. `term_keys[i]` identifies the term
    in slot i so REPEATED phrase terms ("a a") are required to occupy
    distinct document positions, as in Lucene/tantivy. Returns
    (doc_ids, phrase_freqs), unpadded, sorted by doc id.
    """
    if not postings:
        return np.array([], dtype=np.int32), np.array([], dtype=np.int32)

    # intersect doc ids across all terms, tracking each term's posting index
    ids0 = postings[0][0][: dfs[0]]
    common = ids0
    for (ids, _), df in zip(postings[1:], dfs[1:]):
        common = np.intersect1d(common, ids[:df], assume_unique=True)
        if common.size == 0:
            return np.array([], dtype=np.int32), np.array([], dtype=np.int32)

    out_ids: list[int] = []
    out_freqs: list[int] = []
    # per-term posting index of each common doc
    term_indices = []
    for (ids, _), df in zip(postings, dfs):
        term_indices.append(np.searchsorted(ids[:df], common))

    # slots holding the same term must align to distinct positions
    dup_groups: list[list[int]] = []
    if term_keys is not None:
        by_key: dict = {}
        for i, k in enumerate(term_keys):
            by_key.setdefault(k, []).append(i)
        dup_groups = [slots for slots in by_key.values() if len(slots) > 1]

    for row, doc_id in enumerate(common):
        if slop > 0:
            relatives = []
            for i in range(len(postings)):
                offs, data = positions[i]
                ji = term_indices[i][row]
                relatives.append(
                    data[offs[ji]: offs[ji + 1]].astype(np.int64) - i)
            freq = _sloppy_matches(relatives, slop, dup_groups)
            if freq > 0:
                out_ids.append(int(doc_id))
                out_freqs.append(freq)
            continue
        offsets0, data0 = positions[0]
        j0 = term_indices[0][row]
        base = data0[offsets0[j0]: offsets0[j0 + 1]].astype(np.int64)
        for i in range(1, len(postings)):
            offs, data = positions[i]
            ji = term_indices[i][row]
            pos_i = data[offs[ji]: offs[ji + 1]].astype(np.int64)
            base = np.intersect1d(base, pos_i - i, assume_unique=True)
            if base.size == 0:
                break
        if base.size:
            out_ids.append(int(doc_id))
            out_freqs.append(int(base.size))
    return np.array(out_ids, dtype=np.int32), np.array(out_freqs, dtype=np.int32)


def _sloppy_matches(relatives: list[np.ndarray], slop: int,
                    dup_groups: list[list[int]] = ()) -> int:
    """Number of k-way alignments whose relative-position spread <= slop
    (minimal-window sweep with one pointer per term). A window only counts
    when slots of a repeated term (`dup_groups`) sit at distinct absolute
    positions (relative + slot index) — Lucene/tantivy semantics."""
    pointers = [0] * len(relatives)
    matches = 0
    while all(p < len(r) for p, r in zip(pointers, relatives)):
        values = [r[p] for p, r in zip(pointers, relatives)]
        lo, hi = min(values), max(values)
        if hi - lo <= slop and all(
                len({values[i] + i for i in group}) == len(group)
                for group in dup_groups):
            matches += 1
        # advance the minimum pointer to look for further windows
        advance = values.index(lo)
        pointers[advance] += 1
    return matches
