"""Host-side phrase matching over positional postings.

Role of tantivy's `PhraseScorer` in the reference's leaf loop. Phrase
evaluation is a *pre-pass* in this engine: it runs on the (host-resident)
postings + positions of the phrase terms and produces a precomputed posting
list (doc ids + phrase frequencies) that enters the device plan like any
term's postings. This keeps the device graph static while supporting exact
phrases; a Pallas positional kernel is the planned upgrade path.

slop>0 uses the k-way minimal-window algorithm over RELATIVE positions
(p_i - i): an alignment of the phrase terms matches when the spread of
their relative positions is <= slop — tantivy's PhraseScorer semantics.
"""

from __future__ import annotations

import numpy as np


# qwlint: disable-next-line=QW001 - positions arrive as host numpy from
# the split's position index; matching never touches device arrays
def phrase_match(
    postings: list[tuple[np.ndarray, np.ndarray]],
    positions: list[tuple[np.ndarray, np.ndarray]],
    dfs: list[int],
    slop: int = 0,
    term_keys: list | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Docs containing the terms as an exact phrase.

    `postings[i] = (padded_ids, padded_tfs)` and
    `positions[i] = (offsets[post_len+1], data)` for phrase term i, with
    `dfs[i]` real (unpadded) postings. `term_keys[i]` identifies the term
    in slot i so REPEATED phrase terms ("a a") are required to occupy
    distinct document positions, as in Lucene/tantivy. Returns
    (doc_ids, phrase_freqs), unpadded, sorted by doc id.
    """
    if not postings:
        return np.array([], dtype=np.int32), np.array([], dtype=np.int32)

    # intersect doc ids across all terms, tracking each term's posting index
    ids0 = postings[0][0][: dfs[0]]
    common = ids0
    for (ids, _), df in zip(postings[1:], dfs[1:]):
        common = np.intersect1d(common, ids[:df], assume_unique=True)
        if common.size == 0:
            return np.array([], dtype=np.int32), np.array([], dtype=np.int32)

    out_ids: list[int] = []
    out_freqs: list[int] = []
    # per-term posting index of each common doc
    term_indices = []
    for (ids, _), df in zip(postings, dfs):
        term_indices.append(np.searchsorted(ids[:df], common))

    # slots holding the same term must align to distinct positions
    dup_groups: list[list[int]] = []
    if term_keys is not None:
        by_key: dict = {}
        for i, k in enumerate(term_keys):
            by_key.setdefault(k, []).append(i)
        dup_groups = [slots for slots in by_key.values() if len(slots) > 1]

    if slop == 0:
        return _exact_phrase_vectorized(positions, term_indices, common)

    for row, doc_id in enumerate(common):
        relatives = []
        for i in range(len(postings)):
            offs, data = positions[i]
            ji = term_indices[i][row]
            relatives.append(
                data[offs[ji]: offs[ji + 1]].astype(np.int64) - i)
        freq = _sloppy_matches(relatives, slop, dup_groups)
        if freq > 0:
            out_ids.append(int(doc_id))
            out_freqs.append(freq)
    return np.array(out_ids, dtype=np.int32), np.array(out_freqs, dtype=np.int32)


# qwlint: disable-next-line=QW001 - vectorized host numpy inner loop of
# phrase_match (see note there)
def _exact_phrase_vectorized(positions, term_indices, common):
    """slop=0 across ALL common docs at once — no per-doc Python loop.

    Positions of term i are shifted by -i (relative alignment) and encoded
    as doc_row * 2^32 + relative_position; the phrase's alignments are the
    k-way intersection of these encoded sets, and per-doc phrase freqs fall
    out of one bincount. Frequent phrases (10^4+ candidate docs) match in
    milliseconds instead of seconds."""
    base = None
    for i, (offs, data) in enumerate(positions):
        idx = term_indices[i]
        starts = offs[idx].astype(np.int64)
        lens = (offs[idx + 1] - offs[idx]).astype(np.int64)
        total = int(lens.sum())
        if total == 0:
            return (np.array([], dtype=np.int32),
                    np.array([], dtype=np.int32))
        # ragged gather: element j of run r sits at starts[r] + j
        run_of = np.repeat(np.arange(len(idx), dtype=np.int64), lens)
        within = np.arange(total, dtype=np.int64) - \
            np.repeat(np.cumsum(lens) - lens, lens)
        vals = data[starts[run_of] + within].astype(np.int64)
        # +len(positions) keeps the shifted relatives (vals - i) positive
        # for every slot, so the doc-row bits stay clean
        encoded = run_of << np.int64(32) | (vals - i + len(positions))
        base = encoded if base is None else \
            np.intersect1d(base, encoded, assume_unique=True)
        if base.size == 0:
            return (np.array([], dtype=np.int32),
                    np.array([], dtype=np.int32))
    rows = (base >> np.int64(32)).astype(np.int64)
    freqs_per_row = np.bincount(rows, minlength=len(common))
    hit_rows = np.nonzero(freqs_per_row)[0]
    return (common[hit_rows].astype(np.int32),
            freqs_per_row[hit_rows].astype(np.int32))


def _sloppy_matches(relatives: list[np.ndarray], slop: int,
                    dup_groups: list[list[int]] = ()) -> int:
    """Number of k-way alignments whose relative-position spread <= slop
    (minimal-window sweep with one pointer per term). A window only counts
    when slots of a repeated term (`dup_groups`) sit at distinct absolute
    positions (relative + slot index) — Lucene/tantivy semantics."""
    pointers = [0] * len(relatives)
    matches = 0
    while all(p < len(r) for p, r in zip(pointers, relatives)):
        values = [r[p] for p, r in zip(pointers, relatives)]
        lo, hi = min(values), max(values)
        if hi - lo <= slop and all(
                len({values[i] + i for i in group}) == len(group)
                for group in dup_groups):
            matches += 1
        # advance the minimum pointer to look for further windows
        advance = values.index(lo)
        pointers[advance] += 1
    return matches
