"""Host-side phrase matching over positional postings.

Role of tantivy's `PhraseScorer` in the reference's leaf loop. Phrase
evaluation is a *pre-pass* in this engine: it runs on the (host-resident)
postings + positions of the phrase terms and produces a precomputed posting
list (doc ids + phrase frequencies) that enters the device plan like any
term's postings. This keeps the device graph static while supporting exact
phrases; a Pallas positional kernel is the planned upgrade path.

Only slop=0 (exact adjacency) is implemented; non-zero slop raises.
"""

from __future__ import annotations

import numpy as np


def phrase_match(
    postings: list[tuple[np.ndarray, np.ndarray]],
    positions: list[tuple[np.ndarray, np.ndarray]],
    dfs: list[int],
    slop: int = 0,
) -> tuple[np.ndarray, np.ndarray]:
    """Docs containing the terms as an exact phrase.

    `postings[i] = (padded_ids, padded_tfs)` and
    `positions[i] = (offsets[post_len+1], data)` for phrase term i, with
    `dfs[i]` real (unpadded) postings. Returns (doc_ids, phrase_freqs),
    unpadded, sorted by doc id.
    """
    if slop != 0:
        raise NotImplementedError("phrase slop > 0 not supported yet")
    if not postings:
        return np.array([], dtype=np.int32), np.array([], dtype=np.int32)

    # intersect doc ids across all terms, tracking each term's posting index
    ids0 = postings[0][0][: dfs[0]]
    common = ids0
    for (ids, _), df in zip(postings[1:], dfs[1:]):
        common = np.intersect1d(common, ids[:df], assume_unique=True)
        if common.size == 0:
            return np.array([], dtype=np.int32), np.array([], dtype=np.int32)

    out_ids: list[int] = []
    out_freqs: list[int] = []
    # per-term posting index of each common doc
    term_indices = []
    for (ids, _), df in zip(postings, dfs):
        term_indices.append(np.searchsorted(ids[:df], common))

    for row, doc_id in enumerate(common):
        offsets0, data0 = positions[0]
        j0 = term_indices[0][row]
        base = data0[offsets0[j0]: offsets0[j0 + 1]].astype(np.int64)
        for i in range(1, len(postings)):
            offs, data = positions[i]
            ji = term_indices[i][row]
            pos_i = data[offs[ji]: offs[ji + 1]].astype(np.int64)
            base = np.intersect1d(base, pos_i - i, assume_unique=True)
            if base.size == 0:
                break
        if base.size:
            out_ids.append(int(doc_id))
            out_freqs.append(int(base.size))
    return np.array(out_ids, dtype=np.int32), np.array(out_freqs, dtype=np.int32)
