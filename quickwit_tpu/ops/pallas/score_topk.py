"""Pallas TPU kernel: fused BM25 scoring + phase-1 top-k over posting blocks.

The posting-space hot loop (executor.py `_build_posting_space`) is
score → keyed → exact_topk: three HBM round-trips over the [P] posting
arrays. This kernel fuses them: each grid block streams one postings tile
HBM→VMEM, computes BM25 on the VPU, and reduces to its local top-k via an
unrolled iterative max — so scores never materialize in HBM. The host wraps
the [grid, k] block winners with one tiny `lax.top_k`.

Block layout: tiles of (8, 128) f32 respect the VPU tiling constraints
(pallas_guide.md); K iterations of (max, argmax, mask-out) stay in VMEM.

Enable on TPU with QW_PALLAS=1 (default off until hardware-validated;
interpret mode backs the CPU tests either way).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..bm25 import B, K1

BLOCK = 1024            # postings per grid step (8 x 128 f32 tiles)
_SUBLANES = 8
_LANES = 128


def pallas_available() -> bool:
    if os.environ.get("QW_PALLAS") == "1":
        return True
    return False


def _kernel(ids_ref, tfs_ref, norms_ref, scalar_ref, nd_ref, vals_ref, idx_ref,
            *, k: int):
    from jax.experimental import pallas as pl  # noqa: F401 (doc import)

    idf = scalar_ref[0]
    avg_len = scalar_ref[1]
    num_docs = nd_ref[0]  # exact i32 (f32 would round above 2^24)

    ids = ids_ref[...].reshape(_SUBLANES, _LANES * (BLOCK // (_SUBLANES * _LANES)))
    tfs = tfs_ref[...].reshape(ids.shape).astype(jnp.float32)
    norms = norms_ref[...].reshape(ids.shape).astype(jnp.float32)

    denom = tfs + K1 * (1.0 - B + B * norms / jnp.maximum(avg_len, 1e-9))
    scores = (idf * (K1 + 1.0)) * tfs / jnp.maximum(denom, 1e-9)
    valid = (tfs > 0) & (ids < num_docs)
    keyed = jnp.where(valid, scores, -jnp.inf)

    flat = keyed.reshape(-1)
    local = jnp.arange(flat.shape[0], dtype=jnp.int32)
    for j in range(k):
        best = jnp.argmax(flat)
        vals_ref[0, j] = flat[best]
        idx_ref[0, j] = local[best]
        flat = flat.at[best].set(-jnp.inf)


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_score_topk(ids: jnp.ndarray, tfs: jnp.ndarray,
                     norms_gathered: jnp.ndarray, idf: jnp.ndarray,
                     avg_len: jnp.ndarray, num_docs: jnp.ndarray,
                     k: int = 10, interpret: bool = False):
    """(top_values f32[k], posting_indices i32[k]) of BM25 scores over a
    padded posting array. `norms_gathered` = fieldnorms[ids] (XLA gather).
    """
    from jax.experimental import pallas as pl

    num_postings = ids.shape[0]
    padded = ((num_postings + BLOCK - 1) // BLOCK) * BLOCK
    if padded != num_postings:
        pad = padded - num_postings
        ids = jnp.pad(ids, (0, pad), constant_values=2**31 - 1)
        tfs = jnp.pad(tfs, (0, pad))
        norms_gathered = jnp.pad(norms_gathered, (0, pad))
    grid = padded // BLOCK
    scalars = jnp.stack([jnp.asarray(idf, jnp.float32),
                         jnp.asarray(avg_len, jnp.float32)])
    nd = jnp.asarray(num_docs, jnp.int32).reshape(1)

    vals, idx = pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((BLOCK,), lambda i: (i,)),
            pl.BlockSpec((BLOCK,), lambda i: (i,)),
            pl.BlockSpec((BLOCK,), lambda i: (i,)),
            pl.BlockSpec((2,), lambda i: (0,)),
            pl.BlockSpec((1,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((1, k), lambda i: (i, 0)),
            pl.BlockSpec((1, k), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid, k), jnp.float32),
            jax.ShapeDtypeStruct((grid, k), jnp.int32),
        ],
        interpret=interpret,
    )(ids.astype(jnp.int32), tfs, norms_gathered, scalars, nd)

    # phase 2: merge the per-block winners (grid*k elements, tiny)
    block_base = (jnp.arange(grid, dtype=jnp.int32) * BLOCK)[:, None]
    global_idx = (idx + block_base).reshape(-1)
    flat_vals = vals.reshape(-1)
    top_vals, pos = jax.lax.top_k(flat_vals, k)
    return top_vals, global_idx[pos]
