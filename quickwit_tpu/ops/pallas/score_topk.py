"""Pallas TPU kernel: fused BM25 scoring + phase-1 top-k over posting blocks.

The posting-space hot loop (executor.py `_build_posting_space`) is
score → keyed → exact_topk: three HBM round-trips over the [P] posting
arrays. This kernel fuses them: each grid step streams one (64, 128)
postings tile HBM→VMEM, computes BM25 on the VPU, and reduces to its
local top-k with an unrolled max/mask loop — so scores never materialize
in HBM. The host wraps the [grid, k] block winners with one tiny
`lax.top_k`.

Mosaic constraints shape the layout (pallas_guide.md):
- input tiles are 2D (64, 128) — sublane dim divisible by 8, lane dim 128;
  the host reshapes the flat [P] posting arrays to [P/128, 128];
- output blocks are full (8, 128) f32/i32 tiles per grid step (a (1, k)
  block would violate the "last two dims divisible by (8, 128)" rule that
  rejected the first version of this kernel at lowering); only row 0's
  first k lanes carry winners, the rest is -inf/0 padding;
- index bookkeeping uses 2D `broadcasted_iota` (1D iota does not lower);
- ALL block specs are 2D with 2-ary index maps: mixing 1D scalar specs
  (1-ary maps) with 2D data specs in one pallas_call trips an index-map
  legalization bug on this toolchain ("failed to legalize func.return
  (i32, i64)"), so scalars ride in (1, 2)/(1, 1) tiles;
- index maps never return the Python literal 0: under an outer jax.jit
  this toolchain lowers the literal as an i64 constant and Mosaic fails
  to legalize the (i32, i64) index-map return — `i * 0` stays i32.

Hardware validation (v5e, 2026-07-29): winners are bit-identical to the
XLA path at 2M and 20M postings. Timing: the kernel loses to XLA's fused
score+top_k (0.36ms vs 0.085ms at 2M postings, 0.10ms vs 0.03ms at 20M) —
the unrolled top-k costs ~4k full-block VPU passes while `lax.top_k` is a
single optimized pass, and the HBM traffic the fusion saves (the [P]
scores round-trip) is only ~10µs at these sizes. QW_PALLAS therefore
stays default-off: the XLA path is the faster TPU program. The kernel
remains as the validated template for ops XLA cannot fuse (interpret
mode backs the CPU tests either way).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from ..bm25 import B, K1

_ROWS = 64              # sublane rows per grid step
_LANES = 128
BLOCK = _ROWS * _LANES  # postings per grid step (8192)


def pallas_available() -> bool:
    if os.environ.get("QW_PALLAS") == "1":
        return True
    return False


def _kernel(ids_ref, tfs_ref, norms_ref, scalar_ref, nd_ref, vals_ref, idx_ref,
            *, k: int):
    idf = scalar_ref[0, 0]
    avg_len = scalar_ref[0, 1]
    num_docs = nd_ref[0, 0]  # exact i32 (f32 would round above 2^24)

    ids = ids_ref[...]                              # (ROWS, LANES) i32
    tfs = tfs_ref[...].astype(jnp.float32)
    norms = norms_ref[...].astype(jnp.float32)

    denom = tfs + K1 * (1.0 - B + B * norms / jnp.maximum(avg_len, 1e-9))
    scores = (idf * (K1 + 1.0)) * tfs / jnp.maximum(denom, 1e-9)
    valid = (tfs > 0) & (ids < num_docs)
    keyed = jnp.where(valid, scores, -jnp.inf)

    rows, lanes = keyed.shape
    lin = (jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 0) * lanes
           + jax.lax.broadcasted_iota(jnp.int32, (rows, lanes), 1))

    vals_l = []
    idx_l = []
    for _ in range(k):
        best = jnp.max(keyed)
        # first occurrence on ties → lowest in-block posting index
        pos = jnp.min(jnp.where(keyed == best, lin, jnp.int32(2**31 - 1)))
        vals_l.append(best)
        idx_l.append(pos)
        keyed = jnp.where(lin == pos, -jnp.inf, keyed)

    row_v = jnp.concatenate(
        [jnp.stack(vals_l).reshape(1, k),
         jnp.full((1, _LANES - k), -jnp.inf, jnp.float32)], axis=1)
    row_i = jnp.concatenate(
        [jnp.stack(idx_l).reshape(1, k),
         jnp.zeros((1, _LANES - k), jnp.int32)], axis=1)
    vals_ref[...] = jnp.concatenate(
        [row_v, jnp.full((7, _LANES), -jnp.inf, jnp.float32)], axis=0)
    idx_ref[...] = jnp.concatenate(
        [row_i, jnp.zeros((7, _LANES), jnp.int32)], axis=0)


@functools.partial(jax.jit, static_argnames=("k", "interpret"))
def fused_score_topk(ids: jnp.ndarray, tfs: jnp.ndarray,
                     norms_gathered: jnp.ndarray, idf: jnp.ndarray,
                     avg_len: jnp.ndarray, num_docs: jnp.ndarray,
                     k: int = 10, interpret: bool = False):
    """(top_values f32[k], posting_indices i32[k]) of BM25 scores over a
    padded posting array. `norms_gathered` = fieldnorms[ids] (XLA gather).
    """
    from jax.experimental import pallas as pl

    if k > _LANES:
        raise ValueError(f"fused_score_topk supports k <= {_LANES}, got {k}")
    num_postings = ids.shape[0]
    padded = ((num_postings + BLOCK - 1) // BLOCK) * BLOCK
    if padded != num_postings:
        pad = padded - num_postings
        ids = jnp.pad(ids, (0, pad), constant_values=2**31 - 1)
        tfs = jnp.pad(tfs, (0, pad))
        norms_gathered = jnp.pad(norms_gathered, (0, pad))
    grid = padded // BLOCK
    ids2 = ids.astype(jnp.int32).reshape(padded // _LANES, _LANES)
    tfs2 = tfs.reshape(ids2.shape)
    norms2 = norms_gathered.reshape(ids2.shape)
    scalars = jnp.stack([jnp.asarray(idf, jnp.float32),
                         jnp.asarray(avg_len, jnp.float32)]).reshape(1, 2)
    nd = jnp.asarray(num_docs, jnp.int32).reshape(1, 1)

    vals, idx = pl.pallas_call(
        functools.partial(_kernel, k=k),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_ROWS, _LANES), lambda i: (i, i * 0)),
            pl.BlockSpec((_ROWS, _LANES), lambda i: (i, i * 0)),
            pl.BlockSpec((_ROWS, _LANES), lambda i: (i, i * 0)),
            pl.BlockSpec((1, 2), lambda i: (i * 0, i * 0)),
            pl.BlockSpec((1, 1), lambda i: (i * 0, i * 0)),
        ],
        out_specs=[
            pl.BlockSpec((8, _LANES), lambda i: (i, i * 0)),
            pl.BlockSpec((8, _LANES), lambda i: (i, i * 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid * 8, _LANES), jnp.float32),
            jax.ShapeDtypeStruct((grid * 8, _LANES), jnp.int32),
        ],
        interpret=interpret,
    )(ids2, tfs2, norms2, scalars, nd)

    # phase 2: merge the per-block winners (grid*k elements, tiny)
    block_vals = vals.reshape(grid, 8, _LANES)[:, 0, :k]     # (grid, k)
    block_idx = idx.reshape(grid, 8, _LANES)[:, 0, :k]
    block_base = (jnp.arange(grid, dtype=jnp.int32) * BLOCK)[:, None]
    global_idx = (block_idx + block_base).reshape(-1)
    top_vals, pos = jax.lax.top_k(block_vals.reshape(-1), k)
    return top_vals, global_idx[pos]
