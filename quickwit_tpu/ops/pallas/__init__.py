from .score_topk import fused_score_topk, pallas_available

__all__ = ["fused_score_topk", "pallas_available"]
