from . import aggs, bm25, masks, topk

__all__ = ["masks", "bm25", "topk", "aggs"]
