"""Top-K hit collection over dense per-doc arrays.

Role of the reference's monomorphized segment top-K collectors
(`quickwit-search/src/top_k_collector.rs`) and the sort-order semantics of
`collector.rs:1083-1180`: top-K by BM25 score or by a fast-field sort value,
ascending or descending, ties broken by **ascending doc id** — which is
exactly `lax.top_k`'s lowest-index-wins tie rule when the key is laid out
per-doc.

The executor (search/executor.py) builds a unified higher-is-better f64
key per sort spec and calls `exact_topk`; non-matching docs carry -inf,
matching docs missing a sort value carry MISSING_VALUE_SENTINEL.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# Python float literal, NOT a pre-created jnp array: a concrete jax array
# captured into a jitted closure forces a per-call constant sync that is
# catastrophically slow under the axon PJRT plugin (~70ms/call observed).
NEG_INF = float("-inf")

# bottom sentinel for matching-but-missing sort values; MUST be the same
# constant everywhere (executor keying, leaf decode, search_after markers)
# or search_after over missing values loops forever
MISSING_VALUE_SENTINEL = -1.7976931348623157e308

_BLOCK = 1024  # == index.format.DOC_PAD, so dense doc arrays always divide


def exact_topk(x: jnp.ndarray, k: int):
    """Exact top-k, blockwise two-stage.

    XLA's top_k on TPU full-sorts the operand (~66ms for 10M f32); reshaping
    to [G, 1024] blocks, taking per-block top-k, then re-top-k'ing the G*k
    winners is bit-exact (every global winner is a block winner) and ~300x
    faster (0.2ms measured). Tie-breaking is preserved: the flattened
    (block, rank) order equals index order for equal keys.
    """
    n = x.shape[0]
    if n % _BLOCK == 0 and k <= _BLOCK and n // _BLOCK >= 2:
        grid = n // _BLOCK
        vals, idx = lax.top_k(x.reshape(grid, _BLOCK), min(k, _BLOCK))
        flat_idx = (jnp.arange(grid, dtype=jnp.int32)[:, None] * _BLOCK
                    + idx.astype(jnp.int32)).reshape(-1)
        top_vals, pos = lax.top_k(vals.reshape(-1), k)
        return top_vals, flat_idx[pos]
    return lax.top_k(x, k)


def apply_threshold_mask(keyed: jnp.ndarray, threshold) -> jnp.ndarray:
    """Dynamic top-K pruning mask: docs whose internal higher-is-better key
    is STRICTLY below `threshold` (a traced f64 scalar — the collector's
    current Kth sort value) become -inf so `lax.top_k` never surfaces them
    and the packed readback carries fewer live hits.

    `>=` keeps threshold-tying docs: a tie on the primary key can still win
    the (sort_value2, split_id, doc_id) tie-break at the collector, so
    masking them would change results. Non-matching docs are already -inf
    and stay -inf; when threshold == MISSING_VALUE_SENTINEL every matching
    doc (including missing-value docs AT the sentinel) survives.
    """
    return jnp.where(keyed >= threshold, keyed, NEG_INF)


def exact_topk_2key(key1: jnp.ndarray, key2: jnp.ndarray, k: int):
    """Exact lexicographic top-k by (key1, key2) descending, index-ascending
    tie-break — the two-sort-field variant of `exact_topk`, built on
    `lax.sort` with three operands (num_keys=3 sorts ascending by operand 0,
    then 1, then 2). Blockwise two-stage like `exact_topk`: every global
    winner under a lexicographic order is also a block winner.

    Returns (key1_top[k], key2_top[k], indices[k]).
    """
    n = key1.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    neg1, neg2 = -key1, -key2
    if n % _BLOCK == 0 and k <= _BLOCK and n // _BLOCK >= 2:
        grid = n // _BLOCK
        a, b, i = (neg1.reshape(grid, _BLOCK), neg2.reshape(grid, _BLOCK),
                   idx.reshape(grid, _BLOCK))
        sa, sb, si = lax.sort((a, b, i), num_keys=3)
        flat = (sa[:, :k].reshape(-1), sb[:, :k].reshape(-1),
                si[:, :k].reshape(-1))
        fa, fb, fi = lax.sort(flat, num_keys=3)
        return -fa[:k], -fb[:k], fi[:k]
    sa, sb, si = lax.sort((neg1, neg2, idx), num_keys=3)
    return -sa[:k], -sb[:k], si[:k]
