"""Top-K hit collection over dense per-doc arrays.

Role of the reference's monomorphized segment top-K collectors
(`quickwit-search/src/top_k_collector.rs`) and the sort-order semantics of
`collector.rs:1083-1180`: top-K by BM25 score or by a fast-field sort value,
ascending or descending, ties broken by **ascending doc id** — which is
exactly `lax.top_k`'s lowest-index-wins tie rule when the key is laid out
per-doc.

The executor (search/executor.py) builds a unified higher-is-better f64
key per sort spec and calls `exact_topk`; non-matching docs carry -inf,
matching docs missing a sort value carry MISSING_VALUE_SENTINEL.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

# Python float literal, NOT a pre-created jnp array: a concrete jax array
# captured into a jitted closure forces a per-call constant sync that is
# catastrophically slow under the axon PJRT plugin (~70ms/call observed).
NEG_INF = float("-inf")

# bottom sentinel for matching-but-missing sort values; MUST be the same
# constant everywhere (executor keying, leaf decode, search_after markers)
# or search_after over missing values loops forever
MISSING_VALUE_SENTINEL = -1.7976931348623157e308

_BLOCK = 1024  # == index.format.DOC_PAD, so dense doc arrays always divide

# f32's most-negative finite, as a Python float (module-level: computed
# once at import, never inside a traced function)
F32_LOWEST = float(jnp.finfo(jnp.float32).min)

# qwir R2 certification registry: the functions below are the ONLY
# sanctioned f64 sort/top_k sites in the leaf kernel. tools/qwir attributes
# every f64-keyed sort eqn in the audited jaxprs to its defining frame and
# fails the audit unless that frame is certified here (or in the sibling
# registries in search/executor.py and parallel/fanout.py). Justifications
# are part of the certificate — keep them true.
QWIR_CERTIFIED_F64 = {
    "exact_topk": (
        "the exact blockwise two-stage: per-block sorts are fixed at "
        "_BLOCK=1024 lanes and the stage-2 re-top-k runs over G*k winners "
        "— never a corpus-scale full sort (the ~290ms lax.top_k f64 "
        "full-sort this kernel replaced)."),
    "guided_topk": (
        "f32 screen + f64 refine over G*(k+1) gathered candidates with an "
        "exactness certificate; the only f64 top_k runs over the candidate "
        "set, and unsafe screens re-dispatch through exact_topk."),
    "exact_topk_2key": (
        "2-key lexicographic top-k has no f32 screen (distinct f64 "
        "primary keys may collapse in f32 and flip the key2 tie-break); "
        "the f64 lax.sort stays blockwise: 1024-lane block sorts plus a "
        "G*k stage-2, bit-exact by the block-winner argument."),
    "_pad_to_block": (
        "concatenates -inf pad lanes in the operand's own dtype so the "
        "blockwise kernels above apply to non-multiple lengths — padding, "
        "not promotion."),
}


def _pad_to_block(x: jnp.ndarray, k: int):
    """Pad `x` with -inf lanes up to a _BLOCK multiple so the blockwise
    two-stage applies to ANY operand length (posting arrays pad to 128,
    not 1024 — without this, posting-space top-k falls off the blockwise
    path onto `lax.top_k`'s f64 full-sort, ~290ms for a c1-shape operand).

    Bit-exact: pad lanes hold -inf at the highest indices, so every real
    lane ranks at or above every pad lane and lowest-index-wins ties
    resolve inside the real prefix — with k <= n no pad index can ever
    surface in the top-k. Returns None when padding wouldn't enable the
    blockwise path (tiny operand or k > _BLOCK)."""
    n = x.shape[0]
    rem = n % _BLOCK
    if rem == 0 or k > _BLOCK or k > n or (n + _BLOCK - rem) // _BLOCK < 2:
        return None
    pad = _BLOCK - rem
    return jnp.concatenate([x, jnp.full((pad,), NEG_INF, x.dtype)])


def exact_topk(x: jnp.ndarray, k: int):
    """Exact top-k, blockwise two-stage.

    XLA's top_k on TPU full-sorts the operand (~66ms for 10M f32); reshaping
    to [G, 1024] blocks, taking per-block top-k, then re-top-k'ing the G*k
    winners is bit-exact (every global winner is a block winner) and ~300x
    faster (0.2ms measured). Tie-breaking is preserved: the flattened
    (block, rank) order equals index order for equal keys. Non-multiple
    lengths are -inf-padded first (see `_pad_to_block`).
    """
    n = x.shape[0]
    if n % _BLOCK != 0:
        padded = _pad_to_block(x, k)
        if padded is not None:
            x = padded
            n = x.shape[0]
    if n % _BLOCK == 0 and k <= _BLOCK and n // _BLOCK >= 2:
        grid = n // _BLOCK
        vals, idx = lax.top_k(x.reshape(grid, _BLOCK), min(k, _BLOCK))
        flat_idx = (jnp.arange(grid, dtype=jnp.int32)[:, None] * _BLOCK
                    + idx.astype(jnp.int32)).reshape(-1)
        top_vals, pos = lax.top_k(vals.reshape(-1), k)
        return top_vals, flat_idx[pos]
    return lax.top_k(x, k)


def guided_topk(x: jnp.ndarray, k: int):
    """Top-k with an f32-screened candidate set and an exactness certificate.

    `lax.top_k`'s fast CPU path is f32-only: the f64 blockwise `exact_topk`
    on a c1-shape operand costs ~180ms where the f32 equivalent costs ~4ms.
    This variant screens per-block candidates in f32 and refines the G*k
    survivors in f64, returning `(vals, idx, safe)` where `safe` (f64 1/0)
    certifies the result equals `exact_topk(x, k)` bit-for-bit including
    tie-breaks. Callers MUST re-run an exact variant when `safe == 0`
    (executor.py does this host-side after readback — `lax.cond` is not an
    option because vmap lowers it to `select`, executing both branches).

    Exactness argument:
    - The f64→f32 downcast is monotone, so any element excluded by the
      screen with f32 key strictly below a block's k-th screen value is
      f64-dominated by k in-block elements and cannot be a global winner.
    - Ambiguity only arises when a block's (k+1)-th screen value ties its
      k-th (`spill == boundary`): distinct f64 keys may collapse onto the
      tied f32 value and the screen's index-order pick may drop a winner.
      Detected per block in O(G) and reported via `safe`.
    - A boundary tie whose collapse group is f64-PURE (every in-block lane
      at the boundary's f32 value holds the identical f64 key) stays safe:
      within an f64-equal group the screen's lowest-index-wins order IS
      `exact_topk`'s tie order, and any excluded group member is outranked
      by >= k in-block lanes (strictly-greater f32 implies strictly-greater
      f64; equal-f32 picks precede it in index). This is the common case
      for score sorts — a single-term query gives every match the same BM25
      value, so the boundary is one giant exact tie. Checked in O(n) by
      comparing each lane at the boundary's f32 value against the
      boundary's f64 value.
    - Ties at -inf (non-matching) and at the downcast-pinned sentinel
      (`F32_LOWEST` ⟺ MISSING_VALUE_SENTINEL exactly, see below) are
      f64-equal groups subsumed by the purity rule (kept as explicit
      clauses anyway — they are free).
    - Tie-break parity: equal f64 keys are equal in f32, so the screen
      keeps them in ascending-index order within a block, and candidate
      (block, rank) order preserves global index order across blocks.

    To make magnitude-heavy keys (epoch-micros timestamps) f32-stable, real
    values are shifted by the finite minimum before the downcast; sentinel
    and -inf lanes are not shifted. A real lane whose shifted value
    underflows f32's most-negative finite is pinned to `F32_LOWEST`, which
    after the shift (all real lanes >= 0) is occupied ONLY by the sentinel
    — so sentinel ordering survives the downcast exactly.

    The f32 screen's VALUES output is never consumed: deriving the
    boundary/spill check from it makes XLA CPU fall off the TopK fast path
    (~20x; the whole point of this function). The f32 keys of the k+1
    candidates are recomputed from the gathered f64 values instead, and
    only the screen's indices feed the gather.
    """
    n = x.shape[0]
    if n % _BLOCK != 0 and k + 1 <= _BLOCK and k > 0:
        padded = _pad_to_block(x, k)
        if padded is not None:
            # pad lanes are -inf: never shifted, screen to -inf, and their
            # blocks certify safe via the isneginf(boundary) clause
            x = padded
            n = x.shape[0]
    if not (n % _BLOCK == 0 and k + 1 <= _BLOCK and n // _BLOCK >= 2
            and k > 0):
        vals, idx = exact_topk(x, k)
        return vals, idx, jnp.float64(1.0)
    grid = n // _BLOCK

    def downcast(shifted):
        hi = shifted.astype(jnp.float32)
        return jnp.where(jnp.isneginf(hi) & ~jnp.isneginf(shifted),
                         jnp.float32(F32_LOWEST), hi)

    finite_real = x > MISSING_VALUE_SENTINEL
    m = jnp.min(jnp.where(finite_real, x, jnp.inf))
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    shifted = jnp.where(finite_real, x - m, x)
    screen = downcast(shifted).reshape(grid, _BLOCK)
    _, bidx = lax.top_k(screen, k + 1)
    flat_idx = (jnp.arange(grid, dtype=jnp.int32)[:, None] * _BLOCK
                + bidx.astype(jnp.int32)).reshape(-1)
    cand = x[flat_idx]
    cand_shifted = jnp.where(cand > MISSING_VALUE_SENTINEL, cand - m, cand)
    hc = downcast(cand_shifted).reshape(grid, k + 1)
    boundary, spill = hc[:, k - 1], hc[:, k]
    # f64-purity of the boundary collapse group: every in-block lane whose
    # screen value equals the boundary's must hold the boundary's exact f64
    # key (raw domain — equal raw keys shift and downcast identically)
    boundary64 = cand.reshape(grid, k + 1)[:, k - 1]
    pure = jnp.all(jnp.where(screen == boundary[:, None],
                             x.reshape(grid, _BLOCK) == boundary64[:, None],
                             True), axis=1)
    blk_safe = ((spill < boundary) | pure | jnp.isneginf(boundary)
                | (boundary == jnp.float32(F32_LOWEST)))
    safe = jnp.all(blk_safe).astype(jnp.float64)
    # drop the spill column so the refine sees exactly the per-block top-k
    # candidate order `exact_topk` would produce
    cand_k = cand.reshape(grid, k + 1)[:, :k].reshape(-1)
    idx_k = flat_idx.reshape(grid, k + 1)[:, :k].reshape(-1)
    top_vals, pos = lax.top_k(cand_k, k)
    return top_vals, idx_k[pos], safe


def apply_threshold_mask(keyed: jnp.ndarray, threshold) -> jnp.ndarray:
    """Dynamic top-K pruning mask: docs whose internal higher-is-better key
    is STRICTLY below `threshold` (a traced f64 scalar — the collector's
    current Kth sort value) become -inf so `lax.top_k` never surfaces them
    and the packed readback carries fewer live hits.

    `>=` keeps threshold-tying docs: a tie on the primary key can still win
    the (sort_value2, split_id, doc_id) tie-break at the collector, so
    masking them would change results. Non-matching docs are already -inf
    and stay -inf; when threshold == MISSING_VALUE_SENTINEL every matching
    doc (including missing-value docs AT the sentinel) survives.
    """
    return jnp.where(keyed >= threshold, keyed, NEG_INF)


def block_max_threshold_mask(keyed: jnp.ndarray, block_bounds: jnp.ndarray,
                             threshold) -> jnp.ndarray:
    """Impact block-max early exit (format v3): mask WHOLE blocks of the
    posting-space key whose quantized score upper bound cannot reach the
    pushed-down threshold, without scoring them individually.

    `keyed` is the internal higher-is-better f64 key over one term's
    postings (score-descending sorts only — the bound is an upper bound on
    the score itself, so it bounds the internal key only when key ==
    score); `block_bounds` is the per-block f64 bound from
    `bm25.dequantize_block_bounds`, one entry per `keyed.shape[0] //
    nblocks` lanes. `>=` keeps threshold-tying blocks for the same
    tie-break reason as `apply_threshold_mask`: the bound is sound
    (bound >= score always), so a block with bound < threshold contains no
    posting with score >= threshold — masking it to -inf changes nothing
    `apply_threshold_mask` would keep. Survivor blocks pass through
    untouched and are rescored exactly, which is what keeps results
    bit-identical to the unmasked path."""
    nb = block_bounds.shape[0]
    blocks = keyed.reshape(nb, keyed.shape[0] // nb)
    live = (block_bounds >= threshold)[:, None]
    return jnp.where(live, blocks, NEG_INF).reshape(-1)


def merge_topk_chunks(chunks, k: int):
    """Host-side merge of per-chunk top-k results (search/chunkexec.py).

    `chunks` is a list of `(vals, vals2, doc_ids, scores)` tuples — each a
    chunk program's readback, vals descending with the kernel's
    lowest-lane-wins tie-break already applied inside the chunk, `vals2`
    None for single-key sorts, doc ids already rebased to GLOBAL doc space.
    Returns the same 4-tuple truncated/padded to `k`.

    Bit-exactness argument vs the fused kernel: any global top-k lane is a
    top-k lane of its own chunk (same dominance argument as `exact_topk`'s
    blockwise two-stage), so the concatenated per-chunk winners contain the
    global winners. Chunks partition the lane space in ascending lane
    order (posting chunks slice the posting array contiguously; dense
    chunks slice the doc space contiguously), so a STABLE sort of the
    concatenation ordered (chunk, in-chunk rank) reproduces the fused
    kernel's lowest-lane-index tie order exactly. -inf pad lanes sort last
    and are re-padded, never surfacing a fake hit.
    """
    def _cat(column, dtype):
        # qwlint: disable-next-line=QW001 - chunk readbacks are host numpy
        # by contract: each chunk program was read back at its own boundary
        # (the readback IS the boundary), so nothing lives on device here
        return np.concatenate([np.asarray(x, dtype=dtype) for x in column])

    vals = _cat([c[0] for c in chunks], np.float64)
    has2 = chunks[0][1] is not None
    vals2 = _cat([c[1] for c in chunks], np.float64) if has2 else None
    doc_ids = _cat([c[2] for c in chunks], np.int32)
    scores = _cat([c[3] for c in chunks], np.float32)
    # np.lexsort: stable, last key primary; negate for descending. -inf
    # lanes negate to +inf and sink to the tail by the same comparison the
    # device sort uses.
    keys = (-vals,) if vals2 is None else (-vals2, -vals)
    order = np.lexsort(keys)[:k]
    out_vals = np.full(k, NEG_INF, dtype=np.float64)
    out_vals2 = np.full(k, NEG_INF, dtype=np.float64) if has2 else None
    out_ids = np.zeros(k, dtype=np.int32)
    out_scores = np.zeros(k, dtype=np.float32)
    take = len(order)
    out_vals[:take] = vals[order]
    if has2:
        out_vals2[:take] = vals2[order]
    out_ids[:take] = doc_ids[order]
    out_scores[:take] = scores[order]
    return out_vals, out_vals2, out_ids, out_scores


def batched_topk(x: jnp.ndarray, k: int):
    """Per-query exact top-k over a stacked [Q, N] key matrix: the query
    axis of a stacked multi-query dispatch (search/batcher.py). vmap over
    `exact_topk` so each lane runs the SAME blockwise two-stage it would
    run solo — per-query tie-breaks (lowest index wins on equal keys) are
    bit-identical to solo execution by construction, which is what lets a
    stacked group's readback splice against solo baselines. Returns
    `(vals[Q, k], idx[Q, k])`."""
    import jax
    return jax.vmap(lambda row: exact_topk(row, k))(x)


def batched_topk_2key(key1: jnp.ndarray, key2: jnp.ndarray, k: int):
    """Two-sort-field variant of `batched_topk` over stacked [Q, N] key
    matrices. Returns `(key1_top[Q, k], key2_top[Q, k], idx[Q, k])`."""
    import jax
    return jax.vmap(lambda a, b: exact_topk_2key(a, b, k))(key1, key2)


def segment_merge_by_query(values: jnp.ndarray, query_ids: jnp.ndarray,
                           num_queries: int, op: str) -> jnp.ndarray:
    """Mergeable-agg reduction segmented by query id.

    A stacked dispatch's agg accumulators arrive flattened over
    (query lane × shard/chunk): `values` is [Q*S] (or [Q*S, ...] with the
    reduction over axis 0 per segment) and `query_ids` assigns each row to
    its query lane. Segment reduction keeps the merge ONE device op for
    the whole group instead of Q host-side merges — the query-axis
    equivalent of the root's mergeable-agg tree. `op` is the agg's merge
    combinator: "sum" (count/sum/avg numerators), "min", "max".

    Bit-exactness: sum segments accumulate in ascending row order per
    segment (jax segment_sum), matching the solo merge's left fold over
    shards; min/max are order-free.
    """
    import jax
    if op == "sum":
        return jax.ops.segment_sum(values, query_ids,
                                   num_segments=num_queries)
    if op == "min":
        return jax.ops.segment_min(values, query_ids,
                                   num_segments=num_queries)
    if op == "max":
        return jax.ops.segment_max(values, query_ids,
                                   num_segments=num_queries)
    raise ValueError(f"unmergeable segment op: {op!r}")


def exact_topk_2key(key1: jnp.ndarray, key2: jnp.ndarray, k: int):
    """Exact lexicographic top-k by (key1, key2) descending, index-ascending
    tie-break — the two-sort-field variant of `exact_topk`, built on
    `lax.sort` with three operands (num_keys=3 sorts ascending by operand 0,
    then 1, then 2). Blockwise two-stage like `exact_topk`: every global
    winner under a lexicographic order is also a block winner.

    Returns (key1_top[k], key2_top[k], indices[k]).
    """
    n = key1.shape[0]
    if n % _BLOCK != 0:
        p1 = _pad_to_block(key1, k)
        if p1 is not None:
            # pad lanes are (-inf, -inf) at the highest indices: they lose
            # the lexicographic tie-break to every real lane, so with
            # k <= n no pad index can surface (same argument as exact_topk)
            key1 = p1
            key2 = jnp.concatenate([
                key2, jnp.full((p1.shape[0] - n,), NEG_INF, key2.dtype)])
            n = key1.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    neg1, neg2 = -key1, -key2
    if n % _BLOCK == 0 and k <= _BLOCK and n // _BLOCK >= 2:
        grid = n // _BLOCK
        a, b, i = (neg1.reshape(grid, _BLOCK), neg2.reshape(grid, _BLOCK),
                   idx.reshape(grid, _BLOCK))
        sa, sb, si = lax.sort((a, b, i), num_keys=3)
        flat = (sa[:, :k].reshape(-1), sb[:, :k].reshape(-1),
                si[:, :k].reshape(-1))
        fa, fb, fi = lax.sort(flat, num_keys=3)
        return -fa[:k], -fb[:k], fi[:k]
    sa, sb, si = lax.sort((neg1, neg2, idx), num_keys=3)
    return -sa[:k], -sb[:k], si[:k]
