"""Top-K hit collection over dense per-doc arrays.

Role of the reference's monomorphized segment top-K collectors
(`quickwit-search/src/top_k_collector.rs`) and the sort-order semantics of
`collector.rs:1083-1180`: top-K by BM25 score or by a fast-field sort value,
ascending or descending, ties broken by **ascending doc id** — which is
exactly `lax.top_k`'s lowest-index-wins tie rule when the key is laid out
per-doc.

Everything returns fixed-size (k,) arrays plus a match count; invalid slots
(fewer than k matches) are marked with doc_id == -1 after masking host-side
in the collector.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

# Python float literal, NOT a pre-created jnp array: a concrete jax array
# captured into a jitted closure forces a per-call constant sync that is
# catastrophically slow under the axon PJRT plugin (~70ms/call observed).
NEG_INF = float("-inf")

_BLOCK = 1024  # == index.format.DOC_PAD, so dense doc arrays always divide


def exact_topk(x: jnp.ndarray, k: int):
    """Exact top-k, blockwise two-stage.

    XLA's top_k on TPU full-sorts the operand (~66ms for 10M f32); reshaping
    to [G, 1024] blocks, taking per-block top-k, then re-top-k'ing the G*k
    winners is bit-exact (every global winner is a block winner) and ~300x
    faster (0.2ms measured). Tie-breaking is preserved: the flattened
    (block, rank) order equals index order for equal keys.
    """
    n = x.shape[0]
    if n % _BLOCK == 0 and k <= _BLOCK and n // _BLOCK >= 2:
        grid = n // _BLOCK
        vals, idx = lax.top_k(x.reshape(grid, _BLOCK), min(k, _BLOCK))
        flat_idx = (jnp.arange(grid, dtype=jnp.int32)[:, None] * _BLOCK
                    + idx.astype(jnp.int32)).reshape(-1)
        top_vals, pos = lax.top_k(vals.reshape(-1), k)
        return top_vals, flat_idx[pos]
    return lax.top_k(x, k)


def topk_by_score(scores: jnp.ndarray, mask: jnp.ndarray, k: int):
    """(sort_values, doc_ids, match_count) for score-descending top-k.

    `scores` dense [num_docs_padded] f32, `mask` the final query mask.
    Non-matching docs get -inf keys; caller drops slots beyond match_count.
    """
    keyed = jnp.where(mask, scores, NEG_INF)
    values, doc_ids = exact_topk(keyed, k)
    return values, doc_ids.astype(jnp.int32), jnp.sum(mask.astype(jnp.int32))


def topk_by_value(values: jnp.ndarray, present: jnp.ndarray, mask: jnp.ndarray,
                  k: int, descending: bool):
    """Top-k by a numeric sort column. Matching docs without a value sort
    after docs with one; non-matching docs never surface.

    Keys are float64: i64 timestamp columns (micros ~1e15) are exact in f64
    but would collapse to ~minute precision in f32.

    Ascending order negates the key so `lax.top_k`'s max-selection plus
    lowest-index tie-break yields (value asc, doc_id asc) — matching the
    reference's sort semantics (`collector.rs:1083`).
    """
    key = values.astype(jnp.float64)
    if not descending:
        key = -key
    has_value = mask & present.astype(jnp.bool_)
    # matching-but-missing docs get a finite bottom sentinel (above -inf of
    # non-matching docs), so they still fill top-k slots, last.
    missing_sentinel = jnp.float64(-1.7976931348623157e308)
    keyed = jnp.where(has_value, key, jnp.where(mask, missing_sentinel, -jnp.inf))
    top_vals, doc_ids = exact_topk(keyed, k)
    # top_vals stay in "higher is better" key space (ascending sorts keep the
    # negation) — that is the cross-split merge contract of the collector;
    # the leaf converts back to raw values for display.
    return top_vals, doc_ids.astype(jnp.int32), jnp.sum(mask.astype(jnp.int32))


def merge_topk(values_a: jnp.ndarray, ids_a: jnp.ndarray,
               values_b: jnp.ndarray, ids_b: jnp.ndarray, k: int):
    """Merge two sorted top-k lists into one (the ICI tree-reduce step).

    Keys must already be in "descending-is-better" form (ascending sorts are
    pre-negated by the caller). Ties prefer list a then lower doc id, which
    preserves the global tie-break when a holds lower split ordinals.
    """
    values = jnp.concatenate([values_a, values_b])
    ids = jnp.concatenate([ids_a, ids_b])
    top_vals, pos = lax.top_k(values, k)
    return top_vals, ids[pos]
