"""BM25 scoring over padded posting arrays.

Role of tantivy's `Bm25Weight`/`Bm25Scorer` (used by the reference's leaf hot
loop): identical formula and defaults (k1=1.2, b=0.75,
idf = ln(1 + (N - df + 0.5)/(df + 0.5))), but evaluated **vectorized over a
whole posting array at once** — a gather of field norms plus a fused
elementwise expression on the VPU — instead of per-hit scalar math.

Pad slots (tf == 0) score exactly 0, so padded postings need no masking.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

K1 = 1.2
B = 0.75


def idf(num_docs: int, df: int) -> float:
    """Static per-term idf, computed host-side at plan time."""
    return math.log(1.0 + (num_docs - df + 0.5) / (df + 0.5))


def score_postings(tfs: jnp.ndarray, doc_ids: jnp.ndarray,
                   fieldnorms: jnp.ndarray, avg_len: float,
                   idf_value: float, boost: float = 1.0) -> jnp.ndarray:
    """Per-posting BM25 partial scores (float32, same shape as `tfs`).

    `fieldnorms` is the dense per-doc token count; pad posting ids gather a
    clipped norm, but tf==0 zeroes the numerator so pads contribute nothing.
    """
    tf = tfs.astype(jnp.float32)
    norms = fieldnorms[jnp.clip(doc_ids, 0, fieldnorms.shape[0] - 1)].astype(jnp.float32)
    denom = tf + K1 * (1.0 - B + B * norms / jnp.maximum(avg_len, 1e-9))
    return (boost * idf_value * (K1 + 1.0)) * tf / jnp.maximum(denom, 1e-9)


def dequantize_block_bounds(bmax: jnp.ndarray, scale) -> jnp.ndarray:
    """Per-block f64 score upper bounds from the u8 block maxima of an
    impact-ordered term (format v3, index/impact.py).

    `scale` is a traced f64 scalar — the persisted per-term dequantization
    scale with the query boost already folded in host-side at lowering,
    mirroring how boost folds into the idf scalar. Soundness
    (`bmax * scale >= score` for every posting of the block) is the
    writer's quantization contract."""
    return bmax.astype(jnp.float64) * scale
