"""Aggregation kernels with mergeable intermediate states.

Role of the reference's aggregation path (tantivy aggregations driven by
`QuickwitAggregations`, `quickwit-search/src/collector.rs:600`, merged as
serialized intermediate results): each aggregation computes a **fixed-shape
intermediate state** on device (counts / sums / sketch buckets) that merges by
elementwise addition (plus min/max), so the scatter-gather merge tree — and
the multi-chip `psum` — is a pure reduction.

Kernels here: stats state and the percentile sketch. Bucket aggregations
(histogram/date_histogram/terms) are assembled inline by
`search/executor.py::eval_bucket_agg` because they share one bucket-index
computation across counts and per-bucket metrics; the scatter-sentinel
convention (negative indices WRAP in jax scatters, so masked docs are
remapped to a positive out-of-bounds sentinel that mode="drop" drops) is
documented there.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# --- stats -----------------------------------------------------------------

def stats_state(values: jnp.ndarray, present: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """[count, sum, sum_sq, min, max] as float64 — elementwise-mergeable
    (first three add; min/max combine)."""
    m = mask & present.astype(jnp.bool_)
    vals = values.astype(jnp.float64)
    count = jnp.sum(m).astype(jnp.float64)
    s = jnp.sum(jnp.where(m, vals, 0.0))
    s2 = jnp.sum(jnp.where(m, vals * vals, 0.0))
    mn = jnp.min(jnp.where(m, vals, jnp.inf))
    mx = jnp.max(jnp.where(m, vals, -jnp.inf))
    return jnp.stack([count, s, s2, mn, mx])


# --- percentiles (log-linear sketch) --------------------------------------

PCTL_BUCKETS_PER_OCTAVE = 16
PCTL_OCTAVES = 40  # covers 1 .. 2^40 (~1e12); values below 1 land in bucket 0
PCTL_NUM_BUCKETS = PCTL_BUCKETS_PER_OCTAVE * PCTL_OCTAVES


def percentile_sketch(values: jnp.ndarray, present: jnp.ndarray,
                      mask: jnp.ndarray) -> jnp.ndarray:
    """HDR-style log-linear bucket counts [PCTL_NUM_BUCKETS] int32.

    Non-negative values only (durations, sizes); merge = elementwise add.
    Relative error ~ 2^(1/16) per bucket (~4.4%), comparable to ES's default
    t-digest accuracy for tail quantiles.
    """
    m = mask & present.astype(jnp.bool_)
    v = jnp.maximum(values.astype(jnp.float64), 1.0)
    bucket = jnp.floor(jnp.log2(v) * PCTL_BUCKETS_PER_OCTAVE).astype(jnp.int32)
    bucket = jnp.clip(bucket, 0, PCTL_NUM_BUCKETS - 1)
    bucket = jnp.where(m, bucket, jnp.int32(PCTL_NUM_BUCKETS))
    counts = jnp.zeros(PCTL_NUM_BUCKETS, dtype=jnp.int32)
    return counts.at[bucket].add(1, mode="drop")


def sketch_quantiles(counts: np.ndarray, quantiles: list[float]) -> list[float]:
    """Host-side quantile estimation from a (merged) sketch."""
    counts = np.asarray(counts)
    total = counts.sum()
    if total == 0:
        return [float("nan")] * len(quantiles)
    cum = np.cumsum(counts)
    out = []
    for q in quantiles:
        rank = q * total
        bucket = int(np.searchsorted(cum, max(rank, 1), side="left"))
        bucket = min(bucket, len(counts) - 1)
        # bucket midpoint in value space
        lo = 2.0 ** (bucket / PCTL_BUCKETS_PER_OCTAVE)
        hi = 2.0 ** ((bucket + 1) / PCTL_BUCKETS_PER_OCTAVE)
        out.append((lo + hi) / 2.0)
    return out
