"""Aggregation kernels with mergeable intermediate states.

Role of the reference's aggregation path (tantivy aggregations driven by
`QuickwitAggregations`, `quickwit-search/src/collector.rs:600`, merged as
serialized intermediate results): each aggregation computes a **fixed-shape
intermediate state** on device (counts / sums / sketch buckets) that merges by
elementwise addition (plus min/max), so the scatter-gather merge tree — and
the multi-chip `psum` — is a pure reduction.

Kernels here: stats state and the percentile sketch. Bucket aggregations
(histogram/date_histogram/terms) are assembled inline by
`search/executor.py::eval_bucket_agg` because they share one bucket-index
computation across counts and per-bucket metrics; the scatter-sentinel
convention (negative indices WRAP in jax scatters, so masked docs are
remapped to a positive out-of-bounds sentinel that mode="drop" drops) is
documented there.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# --- bucket reductions ------------------------------------------------------
# Scatter-adds into tiny bucket spaces serialize on TPU (65ms for a 4-bucket
# terms count over 10M docs, measured); a compare-and-reduce over a
# broadcast [docs, buckets] predicate fuses onto the VPU instead (0.2ms).
# Above the threshold, collisions spread out and scatter wins on memory.

_COMPARE_MAX_BUCKETS = 256
_COMPARE_MAX_BUCKETS_METRIC = 64


def bucket_counts(idx: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    """Counts per bucket; `idx` int32 with out-of-range sentinel for dropped
    docs (e.g. num_buckets)."""
    if num_buckets <= _COMPARE_MAX_BUCKETS:
        eq = idx[:, None] == jnp.arange(num_buckets, dtype=jnp.int32)[None, :]
        return jnp.sum(eq, axis=0, dtype=jnp.int32)
    return jnp.zeros(num_buckets, dtype=jnp.int32).at[idx].add(1, mode="drop")


def bucket_sum(idx: jnp.ndarray, values: jnp.ndarray, num_buckets: int,
               dtype=jnp.float64) -> jnp.ndarray:
    """Per-bucket sums of `values` (docs with sentinel idx contribute 0)."""
    if num_buckets <= _COMPARE_MAX_BUCKETS_METRIC:
        eq = idx[:, None] == jnp.arange(num_buckets, dtype=jnp.int32)[None, :]
        return jnp.sum(jnp.where(eq, values[:, None].astype(dtype), 0), axis=0)
    return jnp.zeros(num_buckets, dtype=dtype).at[idx].add(
        values.astype(dtype), mode="drop")


def bucket_min(idx: jnp.ndarray, values: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    if num_buckets <= _COMPARE_MAX_BUCKETS_METRIC:
        eq = idx[:, None] == jnp.arange(num_buckets, dtype=jnp.int32)[None, :]
        return jnp.min(jnp.where(eq, values[:, None].astype(jnp.float64), jnp.inf), axis=0)
    return jnp.full(num_buckets, jnp.inf, dtype=jnp.float64).at[idx].min(
        values.astype(jnp.float64), mode="drop")


def bucket_max(idx: jnp.ndarray, values: jnp.ndarray, num_buckets: int) -> jnp.ndarray:
    if num_buckets <= _COMPARE_MAX_BUCKETS_METRIC:
        eq = idx[:, None] == jnp.arange(num_buckets, dtype=jnp.int32)[None, :]
        return jnp.max(jnp.where(eq, values[:, None].astype(jnp.float64), -jnp.inf), axis=0)
    return jnp.full(num_buckets, -jnp.inf, dtype=jnp.float64).at[idx].max(
        values.astype(jnp.float64), mode="drop")


# --- stats -----------------------------------------------------------------

def stats_state(values: jnp.ndarray, present: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """[count, sum, sum_sq, min, max] as float64 — elementwise-mergeable
    (first three add; min/max combine)."""
    m = mask & present.astype(jnp.bool_)
    vals = values.astype(jnp.float64)
    count = jnp.sum(m).astype(jnp.float64)
    s = jnp.sum(jnp.where(m, vals, 0.0))
    s2 = jnp.sum(jnp.where(m, vals * vals, 0.0))
    mn = jnp.min(jnp.where(m, vals, jnp.inf))
    mx = jnp.max(jnp.where(m, vals, -jnp.inf))
    return jnp.stack([count, s, s2, mn, mx])


def merge_stats_states(a, b) -> np.ndarray:
    """Merge two `stats_state` partials ([count, sum, sum_sq, min, max]).

    The layout contract lives here, next to the kernel that emits it: the
    first three components add, min/max combine — which is what makes the
    per-split partials a pure fixed-shape reduction (associative and
    commutative), mergeable host-side at the collector or on device under
    `psum`. Operates on host numpy (post-readback partials)."""
    # qwlint: disable-next-line=QW001 - post-readback host partials by contract
    a, b = np.asarray(a), np.asarray(b)
    return np.array([a[0] + b[0], a[1] + b[1], a[2] + b[2],
                     min(a[3], b[3]), max(a[4], b[4])])


# --- percentiles (DDSketch-compatible log buckets) ------------------------
#
# Bucket mapping matches the sketch the reference drives through tantivy
# (sketches-ddsketch with 1% relative accuracy): γ = (1+α)/(1-α) with
# α = 0.01, a value v > 0 lands in bucket k = ceil(log_γ v), and the
# bucket reports 2γ^k/(γ+1) — verified to reproduce the reference
# conformance corpus values to ~1e-12 (e.g. 100 → 100.49456770856...).
# Non-positive values land in the underflow bucket (reported 0.0);
# positive values below the k-range clip to the FIRST real bucket
# (reported ~2.8e-10 — closer to truth than 0 for tiny durations).

PCTL_ALPHA = 0.01
PCTL_GAMMA = (1.0 + PCTL_ALPHA) / (1.0 - PCTL_ALPHA)
_PCTL_LN_GAMMA = float(np.log(PCTL_GAMMA))
PCTL_K_MIN = -1100   # v ≈ 2.8e-10
PCTL_K_MAX = 1500    # v ≈ 1.1e13
PCTL_NUM_BUCKETS = PCTL_K_MAX - PCTL_K_MIN + 2  # +underflow bucket 0


def percentile_sketch(values: jnp.ndarray, present: jnp.ndarray,
                      mask: jnp.ndarray) -> jnp.ndarray:
    """DDSketch bucket counts [PCTL_NUM_BUCKETS] int32.

    Positive values (durations, sizes); merge = elementwise add."""
    m = mask & present.astype(jnp.bool_)
    bucket = jnp.where(m, _pctl_bucket(values), jnp.int32(PCTL_NUM_BUCKETS))
    counts = jnp.zeros(PCTL_NUM_BUCKETS, dtype=jnp.int32)
    return counts.at[bucket].add(1, mode="drop")


def _pctl_bucket(values: jnp.ndarray) -> jnp.ndarray:
    """Value → DDSketch bucket index (shared by the global and per-bucket
    sketch builders so their resolution can never drift)."""
    v = values.astype(jnp.float64)
    positive = v > 0.0
    k = jnp.ceil(jnp.log(jnp.maximum(v, 1e-300)) / _PCTL_LN_GAMMA)
    idx = jnp.clip(k.astype(jnp.int32) - PCTL_K_MIN + 1,
                   1, PCTL_NUM_BUCKETS - 1)
    return jnp.where(positive, idx, jnp.int32(0))


def bucket_percentile_sketch(idx: jnp.ndarray, values: jnp.ndarray,
                             num_buckets: int) -> jnp.ndarray:
    """Per-bucket HDR sketches [num_buckets, PCTL_NUM_BUCKETS] int32.

    `idx` int32 with out-of-range sentinel (num_buckets) for dropped docs.
    One scatter-add into the flattened [nb * PCTL] space (large enough that
    XLA's scatter path beats compare-reduce here)."""
    sb = _pctl_bucket(values)
    flat = jnp.where(idx < num_buckets, idx * PCTL_NUM_BUCKETS + sb,
                     jnp.int32(num_buckets * PCTL_NUM_BUCKETS))
    counts = jnp.zeros(num_buckets * PCTL_NUM_BUCKETS, dtype=jnp.int32)
    return counts.at[flat].add(1, mode="drop").reshape(
        num_buckets, PCTL_NUM_BUCKETS)


# qwlint: disable-next-line=QW001 - root-side finalize over a host numpy
# sketch already shipped from the leaves; no device data in sight
def sketch_quantiles(counts: np.ndarray, quantiles: list[float]) -> list[float]:
    """Host-side quantile estimation from a (merged) sketch."""
    counts = np.asarray(counts)
    total = counts.sum()
    if total == 0:
        return [float("nan")] * len(quantiles)
    cum = np.cumsum(counts)
    out = []
    for q in quantiles:
        # DDSketch (sketches-ddsketch crate, used by tantivy) rank rule:
        # rank = floor(q·(n-1)), return the first bucket whose cumulative
        # count strictly exceeds it — i.e. the 0-based rank-th item.
        # (p85 of {30,130} → 30's bucket, median of 5 → the 3rd item.)
        rank = int(np.floor(q * (total - 1)))
        target = min(rank + 1, int(total))
        bucket = int(np.searchsorted(cum, target, side="left"))
        bucket = min(bucket, len(counts) - 1)
        if bucket == 0:
            out.append(0.0)
        else:
            k = bucket + PCTL_K_MIN - 1
            out.append(2.0 * PCTL_GAMMA ** k / (PCTL_GAMMA + 1.0))
    return out


# --- cardinality (HyperLogLog) ---------------------------------------------
# 256 registers (p=8, ~6.5% relative error — matching the tolerance band of
# ES's default-precision cardinality). The register vector is the mergeable
# state: cross-split/cross-chip merge is an elementwise max, so it rides the
# same psum-style reduction tree as the other agg states (with max instead
# of add). Register updates use the compare-and-reduce pattern (scatter-max
# into 256 buckets serializes on TPU, same pathology as bucket_counts).

HLL_NUM_REGISTERS = 256
_HLL_P = 8


def hll_hash_bytes(data: bytes) -> int:
    """Host-side hashing of term strings so that identical terms hash
    identically across splits regardless of their ordinals: 64-bit
    FNV-1a + the splitmix64 finalizer. The finalizer is ESSENTIAL —
    HLL's register index is the hash's TOP bits, and raw FNV-1a of
    short, similar terms ("svc0".."svc6") barely diffuses trailing-byte
    differences upward, collapsing every term into one register (a
    cardinality of ~1). The numeric path applies the same finalizer on
    device (_hll_mix64)."""
    h = 0xcbf29ce484222325
    for b in data:
        h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    # splitmix64 finalizer (keep in lockstep with _hll_mix64)
    h = ((h ^ (h >> 30)) * 0xbf58476d1ce4e5b9) & 0xFFFFFFFFFFFFFFFF
    h = ((h ^ (h >> 27)) * 0x94d049bb133111eb) & 0xFFFFFFFFFFFFFFFF
    return h ^ (h >> 31)


def _hll_mix64(x: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer on uint64 (i64 ops are emulated on TPU but this
    runs once per doc over fused elementwise ops)."""
    x = (x ^ (x >> 30)) * jnp.uint64(0xbf58476d1ce4e5b9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94d049bb133111eb)
    return x ^ (x >> 31)


def _hll_reg_rho(hashes: jnp.ndarray, valid: jnp.ndarray):
    """(register index, rho) per doc: register = top p hash bits, rho =
    1 + leading zeros of the suffix (capped). Invalid docs get rho 0 and
    the out-of-range register sentinel."""
    reg = (hashes >> jnp.uint64(64 - _HLL_P)).astype(jnp.int32)
    suffix = hashes << jnp.uint64(_HLL_P)
    # leading-zero count of the 64-bit suffix via float exponent is
    # imprecise; use a branchless binary clz on uint64
    clz = jnp.zeros(suffix.shape, dtype=jnp.int32)
    x = suffix
    for shift in (32, 16, 8, 4, 2, 1):
        mask_hi = x >> jnp.uint64(64 - shift)
        zero_hi = mask_hi == 0
        clz = clz + jnp.where(zero_hi, shift, 0)
        x = jnp.where(zero_hi, x << jnp.uint64(shift), x)
    rho = jnp.minimum(clz + 1, 64 - _HLL_P).astype(jnp.int32)
    rho = jnp.where(valid, rho, 0)
    reg = jnp.where(valid, reg, jnp.int32(HLL_NUM_REGISTERS))
    return reg, rho


def hll_registers(hashes: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """[HLL_NUM_REGISTERS] int32 register vector (max of rho per register).

    `hashes` uint64 per doc, `valid` bool per doc."""
    reg, rho = _hll_reg_rho(hashes, valid)
    eq = reg[:, None] == jnp.arange(HLL_NUM_REGISTERS,
                                    dtype=jnp.int32)[None, :]
    return jnp.max(jnp.where(eq, rho[:, None], 0), axis=0)


def bucket_hll_registers(idx: jnp.ndarray, hashes: jnp.ndarray,
                         valid: jnp.ndarray,
                         num_buckets: int) -> jnp.ndarray:
    """Per-bucket HLL registers [num_buckets, HLL_NUM_REGISTERS] int32 —
    cardinality as a bucket sub-metric: one scatter-MAX into the
    flattened [nb * registers] space (the per-bucket twin of
    bucket_percentile_sketch's scatter-add)."""
    reg, rho = _hll_reg_rho(hashes, valid)
    ok = valid & (idx < num_buckets)
    flat = jnp.where(ok, idx * HLL_NUM_REGISTERS + reg,
                     jnp.int32(num_buckets * HLL_NUM_REGISTERS))
    out = jnp.zeros(num_buckets * HLL_NUM_REGISTERS, dtype=jnp.int32)
    return out.at[flat].max(rho, mode="drop").reshape(
        num_buckets, HLL_NUM_REGISTERS)


def hll_from_numeric(values: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Registers for a numeric column: hash the 64-bit value pattern."""
    bits = values.astype(jnp.int64).astype(jnp.uint64) \
        if values.dtype != jnp.float64 \
        else jax_bitcast_f64(values)
    return hll_registers(_hll_mix64(bits), valid)


def jax_bitcast_f64(values: jnp.ndarray) -> jnp.ndarray:
    import jax
    return jax.lax.bitcast_convert_type(values, jnp.uint64)


# qwlint: disable-next-line=QW001 - host-side HLL bias correction on the
# merged register array (root finalize, off the dispatch path)
def hll_estimate(registers: np.ndarray) -> float:
    """Classic HLL estimate with small-range (linear counting) correction."""
    registers = np.asarray(registers, dtype=np.float64)
    m = float(HLL_NUM_REGISTERS)
    alpha = 0.7213 / (1 + 1.079 / m)
    harmonic = np.sum(np.exp2(-registers))
    estimate = alpha * m * m / harmonic
    zeros = float(np.sum(registers == 0))
    if estimate <= 2.5 * m and zeros > 0:
        estimate = m * np.log(m / zeros)
    return float(estimate)
