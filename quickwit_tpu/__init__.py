"""quickwit_tpu — a TPU-native distributed search engine.

A from-scratch reimplementation of the capabilities of quickwit
(https://github.com/quickwit-oss/quickwit): sub-second full-text search and
ES-compatible aggregations over immutable index "splits" stored on object
storage, with decoupled stateless indexers and searchers.

Unlike the Rust/tantivy reference, the leaf-search hot path — term/range
filtering, BM25 scoring, top-K collection, and columnar aggregations — runs
as JAX/XLA (and Pallas) kernels over HBM-resident dense arrays, and the
scatter-gather merge tree is a sharded top-K + aggregation reduce over a
`jax.sharding.Mesh` (ICI collectives) instead of per-node gRPC fan-in.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

- ``common``        foundation utilities (reference: quickwit-common)
- ``config``        node/index/source config (reference: quickwit-config)
- ``query``         serializable QueryAst + parsers (reference: quickwit-query)
- ``models``        doc mapping, split/index metadata (quickwit-doc-mapper,
                    quickwit-metastore's SplitMetadata)
- ``storage``       object-storage abstraction + caches (quickwit-storage)
- ``index``         TPU-first split format: blocked postings, columns,
                    doc store, hotcache (quickwit-directories + tantivy fmt)
- ``ops``           JAX/Pallas kernels: masks, BM25, top-K, aggregations
- ``search``        leaf/root search, collectors, caches (quickwit-search)
- ``parallel``      mesh fan-out + ICI merge tree (the pmap'd merge of
                    BASELINE.json)
- ``indexing``      split building pipeline + merges (quickwit-indexing)
- ``ingest``        WAL-backed ingest, router/ingester (quickwit-ingest)
- ``metastore``     file-backed metastore + publish protocol
- ``cluster``       membership + failure detection (quickwit-cluster)
- ``control_plane`` indexing plan scheduler (quickwit-control-plane)
- ``janitor``       GC + retention (quickwit-janitor)
- ``serve``         REST + ES-compatible APIs (quickwit-serve)
"""

__version__ = "0.1.0"

# i64 timestamp columns (micros since epoch, ~1e15) and f64 aggregation
# accumulators need 64-bit math; f64 is exact for integers < 2^53 which covers
# all datetime micros. Must be set before any tracing.
import os as _os

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# Platform override: the environment's sitecustomize may force-register an
# accelerator plugin and rewrite jax_platforms, ignoring JAX_PLATFORMS;
# QW_JAX_PLATFORM lets operators (and the CLI) pin the backend explicitly —
# e.g. QW_JAX_PLATFORM=cpu for host-only roles or when no TPU is reachable.
_platform = _os.environ.get("QW_JAX_PLATFORM")
if _platform:
    _jax.config.update("jax_platforms", _platform)
    if _platform == "cpu" and _os.environ.get("QW_NUM_CPU_DEVICES"):
        _jax.config.update("jax_num_cpu_devices",
                           int(_os.environ["QW_NUM_CPU_DEVICES"]))
