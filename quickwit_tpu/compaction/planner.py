"""Compaction planner: merge-task planning with in-flight tracking.

Role of the reference's standalone compaction planner
(`quickwit-compaction/src/planner/compaction_planner.rs`): each tick it
re-scans the immature published split set per index (most-urgent first),
runs the index's merge policy, and emits merge tasks — EXCLUDING splits
already claimed by an in-flight task, so a slow merge is never
double-scheduled. Completed/failed/expired tasks release their claims.

The planner is deliberately stateless across restarts (like the
reference: "wait for two intervals to let in-progress workers report"
— here a fresh planner simply re-plans; the metastore's replace-splits
publish is idempotent per input set, and executors fail cleanly when an
input split was already replaced)."""

from __future__ import annotations

import logging
import threading
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

from ..common.clock import monotonic
from ..indexing.merge import merge_policy_from_config
from ..metastore.base import ListSplitsQuery, Metastore
from ..models.split_metadata import Split, SplitState

logger = logging.getLogger(__name__)

# cap on splits considered per index per tick (reference
# MAX_SPLITS_PER_TICK rationale: a backlog bubbles into range as the
# front of the queue merges off)
MAX_SPLITS_PER_TICK = 1000


@dataclass
class MergeTask:
    task_id: str
    index_uid: str
    split_ids: tuple[str, ...]
    created_at: float = 0.0


@dataclass
class _InFlight:
    task: MergeTask
    deadline: float


class CompactionPlanner:
    """Plans merge tasks over the metastore's published split set."""

    def __init__(self, metastore: Metastore,
                 task_timeout_secs: float = 600.0,
                 clock: Callable[[], float] = monotonic):
        self.metastore = metastore
        self.task_timeout_secs = task_timeout_secs
        self.clock = clock
        # completion hooks fire on merge WORKER threads while plan()
        # runs on the tick thread — every _in_flight access locks
        # qwlint: disable-next-line=QW008 - compaction supervisor background
        # loop, outside the DST-raced path; leaf primitives only
        self._lock = threading.Lock()
        self._in_flight: dict[str, _InFlight] = {}

    # -- claims --------------------------------------------------------
    def _claimed_split_ids(self) -> set[str]:
        now = self.clock()
        with self._lock:
            expired = [tid for tid, inf in self._in_flight.items()
                       if inf.deadline < now]
            for tid in expired:
                task = self._in_flight.pop(tid).task
                logger.warning("merge task %s on %s timed out; "
                               "re-planning its splits", tid,
                               task.index_uid)
            return {sid for inf in self._in_flight.values()
                    for sid in inf.task.split_ids}

    def complete_task(self, task_id: str) -> None:
        with self._lock:
            self._in_flight.pop(task_id, None)

    def fail_task(self, task_id: str) -> None:
        """Failed merges release their claim immediately (the reference's
        pipelines own retries; re-planning reissues the same merge)."""
        with self._lock:
            self._in_flight.pop(task_id, None)

    @property
    def num_in_flight(self) -> int:
        with self._lock:
            return len(self._in_flight)

    # -- planning ------------------------------------------------------
    def plan(self, index_uids: Optional[list[str]] = None,
             max_tasks: Optional[int] = None,
             indexes: Optional[list] = None) -> list[MergeTask]:
        """One planning tick → new merge tasks (claims recorded).
        `indexes` short-circuits the metastore scan when the caller
        already fetched the metadata (the node's tick does)."""
        claimed = self._claimed_split_ids()
        tasks: list[MergeTask] = []
        if indexes is None:
            indexes = self.metastore.list_indexes()
        for metadata in indexes:
            if index_uids is not None and \
                    metadata.index_uid not in index_uids:
                continue
            policy = merge_policy_from_config(
                metadata.index_config.merge_policy)
            splits = self.metastore.list_splits(ListSplitsQuery(
                index_uids=[metadata.index_uid],
                states=[SplitState.PUBLISHED]))
            # most-urgent first: oldest splits merge first under backlog
            splits.sort(key=lambda s: s.metadata.split_id)
            eligible: list[Split] = [
                s for s in splits[:MAX_SPLITS_PER_TICK]
                if s.metadata.split_id not in claimed]
            for operation in policy.operations(eligible):
                task = MergeTask(
                    task_id=uuid.uuid4().hex[:16],
                    index_uid=metadata.index_uid,
                    split_ids=tuple(s.metadata.split_id
                                    for s in operation.splits),
                    created_at=self.clock())
                with self._lock:
                    self._in_flight[task.task_id] = _InFlight(
                        task, self.clock() + self.task_timeout_secs)
                claimed.update(task.split_ids)
                tasks.append(task)
                if max_tasks is not None and len(tasks) >= max_tasks:
                    return tasks
        return tasks
