from .planner import CompactionPlanner, MergeTask
from .supervisor import CompactorState, CompactorSupervisor

__all__ = ["CompactionPlanner", "CompactorState", "CompactorSupervisor",
           "MergeTask"]
