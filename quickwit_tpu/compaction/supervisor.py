"""Compactor supervisor: a bounded pool of merge executions + lifecycle.

Role of the reference's `compactor_supervisor.rs`: a compactor node
accepts merge tasks up to `max_concurrent_merges` slots, executes each
through the MergeExecutor (the reference's CompactionPipeline), and
supports decommission — Draining rejects new tasks (reports zero free
slots) while in-flight merges finish, then Drained."""

from __future__ import annotations

import enum
import logging
import threading
from typing import Callable, Optional

from ..indexing.merge import MergeExecutor, MergeOperation
from ..metastore.base import ListSplitsQuery, Metastore
from ..models.split_metadata import SplitState
from .planner import MergeTask

logger = logging.getLogger(__name__)


class CompactorState(enum.Enum):
    RUNNING = "running"
    DRAINING = "draining"
    DRAINED = "drained"


class CompactorSupervisor:
    def __init__(self, metastore: Metastore, storage_resolver,
                 node_id: str = "compactor-0",
                 max_concurrent_merges: int = 2):
        self.metastore = metastore
        self.storage_resolver = storage_resolver
        self.node_id = node_id
        self.max_concurrent_merges = max_concurrent_merges
        # qwlint: disable-next-line=QW008 - compaction supervisor background
        # loop, outside the DST-raced path; leaf primitives only
        self._lock = threading.Lock()
        self._active: set[str] = set()
        self._state = CompactorState.RUNNING
        # qwlint: disable-next-line=QW008 - compaction supervisor background
        # loop, outside the DST-raced path; leaf primitives only
        self._drained = threading.Event()
        self.num_completed = 0
        self.num_failed = 0

    # -- status --------------------------------------------------------
    @property
    def state(self) -> CompactorState:
        with self._lock:
            return self._state

    def available_slots(self) -> int:
        with self._lock:
            if self._state is not CompactorState.RUNNING:
                return 0  # draining compactors report zero capacity
            return max(0, self.max_concurrent_merges - len(self._active))

    def status(self) -> dict:
        with self._lock:
            return {"state": self._state.value,
                    "active_tasks": sorted(self._active),
                    "available_slots":
                        0 if self._state is not CompactorState.RUNNING
                        else max(0, self.max_concurrent_merges
                                 - len(self._active)),
                    "num_completed": self.num_completed,
                    "num_failed": self.num_failed}

    # -- execution -----------------------------------------------------
    def submit(self, task: MergeTask,
               on_done: Optional[Callable[[MergeTask, bool], None]] = None,
               synchronous: bool = False) -> bool:
        """Accept a merge task if a slot is free. `on_done(task, ok)`
        fires after execution (the planner's completion hook)."""
        with self._lock:
            if self._state is not CompactorState.RUNNING:
                return False
            if len(self._active) >= self.max_concurrent_merges:
                return False
            self._active.add(task.task_id)
        if synchronous:
            self._execute(task, on_done)
        else:
            # qwlint: disable-next-line=QW003 - merge tasks are background
            # maintenance; they must NOT inherit a submitting query's
            # deadline or the merge would be shed mid-write
            # qwlint: disable-next-line=QW008 - compaction supervisor
            # background loop, outside the DST-raced path; leaf primitives only
            threading.Thread(
                target=self._execute, args=(task, on_done),
                name=f"merge-{task.task_id}", daemon=True).start()
        return True

    def _execute(self, task: MergeTask, on_done):
        ok = False
        try:
            ok = self._run_merge(task)
        except Exception:  # noqa: BLE001 - supervised execution
            logger.exception("merge task %s failed", task.task_id)
        finally:
            with self._lock:
                self._active.discard(task.task_id)
                if ok:
                    self.num_completed += 1
                else:
                    self.num_failed += 1
                if (self._state is CompactorState.DRAINING
                        and not self._active):
                    self._state = CompactorState.DRAINED
                    self._drained.set()
            if on_done is not None:
                on_done(task, ok)

    def _run_merge(self, task: MergeTask) -> bool:
        for metadata in self.metastore.list_indexes():
            if metadata.index_uid == task.index_uid:
                break
        else:
            logger.warning("merge task %s: index %s is gone",
                           task.task_id, task.index_uid)
            return False
        want = set(task.split_ids)
        splits = [s for s in self.metastore.list_splits(ListSplitsQuery(
            index_uids=[task.index_uid], states=[SplitState.PUBLISHED]))
            if s.metadata.split_id in want]
        if len(splits) != len(want):
            # an input was already replaced (e.g. by a pre-split-brain
            # merge): abandoning is safe, the planner re-plans
            logger.info("merge task %s: inputs changed; skipping",
                        task.task_id)
            return False
        storage = self.storage_resolver.resolve(
            metadata.index_config.index_uri)
        executor = MergeExecutor(task.index_uid,
                                 metadata.index_config.doc_mapper,
                                 self.metastore, storage, self.node_id)
        delete_tasks = self.metastore.list_delete_tasks(task.index_uid)
        executor.execute(MergeOperation(tuple(splits)),
                         delete_tasks=delete_tasks or None)
        return True

    # -- lifecycle -----------------------------------------------------
    def decommission(self, timeout: Optional[float] = None) -> bool:
        """Stop accepting tasks; wait for in-flight merges to finish."""
        with self._lock:
            if self._state is CompactorState.DRAINED:
                return True
            self._state = CompactorState.DRAINING
            if not self._active:
                self._state = CompactorState.DRAINED
                self._drained.set()
                return True
        return self._drained.wait(timeout=timeout)
