"""S3-compatible object storage on the Python stdlib.

Role of the reference's
`quickwit-storage/src/object_storage/s3_compatible_storage.rs:1`: the
primary production backend — splits, metastore files, and WAL snapshots
all live in a bucket; searchers stay stateless because every byte is a
ranged GET away. The reference uses the AWS SDK; this image has no SDK,
so the S3 REST API is spoken directly over `http.client` with SigV4
request signing built from `hmac`/`hashlib` (the protocol is small:
canonical request → string-to-sign → derived signing key).

Works against AWS S3 and any S3-compatible endpoint (MinIO, the
in-process `fake_s3` test server) via path-style addressing.

Concurrency: one pooled HTTP connection per (thread, endpoint) —
`http.client` connections are not thread-safe, and the warmup path
issues ranged GETs from a thread pool.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import os
import socket
import threading
import time
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Iterable, Optional

from ..common.uri import Uri
from .base import StorageError
from .http_object import HttpObjectStorage

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()
_RETRYABLE_STATUS = (500, 502, 503, 504)
_MAX_ATTEMPTS = 3
_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


@dataclass
class S3Config:
    """Connection/credential config, resolved from the environment by
    default (the same variables the AWS SDK reads)."""
    endpoint: str = ""          # e.g. "http://127.0.0.1:9000"; "" = AWS
    region: str = "us-east-1"
    access_key: str = ""
    secret_key: str = ""
    session_token: Optional[str] = None
    request_timeout_secs: float = 30.0

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "S3Config":
        env = env if env is not None else os.environ
        return S3Config(
            endpoint=env.get("QW_S3_ENDPOINT", env.get("AWS_ENDPOINT_URL", "")),
            region=env.get("AWS_REGION", env.get("AWS_DEFAULT_REGION",
                                                 "us-east-1")),
            access_key=env.get("AWS_ACCESS_KEY_ID", ""),
            secret_key=env.get("AWS_SECRET_ACCESS_KEY", ""),
            session_token=env.get("AWS_SESSION_TOKEN"),
        )


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(method: str, host: str, canonical_uri: str,
                  query: list[tuple[str, str]], payload_sha256: str,
                  config: S3Config,
                  now: Optional[datetime.datetime] = None,
                  extra_headers: Optional[dict[str, str]] = None,
                  service: str = "s3") -> dict[str, str]:
    """AWS Signature Version 4 for one request. Returns the headers to
    send (including Authorization). Exposed for direct testing against
    the published AWS test vectors, and reused by other AWS-API clients
    (Kinesis source) via `service`."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    headers = {"host": host, "x-amz-content-sha256": payload_sha256,
               "x-amz-date": amz_date}
    if config.session_token:
        headers["x-amz-security-token"] = config.session_token
    if extra_headers:
        headers.update({k.lower(): v for k, v in extra_headers.items()})

    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(query))
    signed_names = sorted(headers)
    canonical_headers = "".join(f"{k}:{headers[k].strip()}\n"
                                for k in signed_names)
    signed_headers = ";".join(signed_names)
    canonical_request = "\n".join([
        method, canonical_uri, canonical_query, canonical_headers,
        signed_headers, payload_sha256])

    scope = f"{datestamp}/{config.region}/{service}/aws4_request"
    string_to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical_request.encode()).hexdigest()])

    key = _sign(f"AWS4{config.secret_key}".encode(), datestamp)
    key = _sign(key, config.region)
    key = _sign(key, service)
    key = _sign(key, "aws4_request")
    signature = hmac.new(key, string_to_sign.encode(),
                         hashlib.sha256).hexdigest()

    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={config.access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return headers


class S3CompatibleStorage(HttpObjectStorage):
    """`Storage` over the S3 REST API with SigV4 and path-style
    addressing. URI shape: `s3://bucket/prefix`. Connection pool, retry
    loop, and read paths live in HttpObjectStorage; this class adds the
    SigV4 signing hook and S3-specific operations."""

    service_name = "s3"

    def __init__(self, uri: Uri, config: Optional[S3Config] = None):
        self.config = config or S3Config.from_env()
        super().__init__(uri, self.config.request_timeout_secs)
        parts = uri.path.lstrip("/").split("/", 1)
        self.bucket = parts[0]
        self.prefix = parts[1].strip("/") if len(parts) > 1 else ""
        if not self.bucket:
            raise StorageError(f"s3 uri has no bucket: {uri}")
        self._init_endpoint(self.config.endpoint or
                            f"https://s3.{self.config.region}.amazonaws.com")

    @property
    def _root_segment(self) -> str:
        return self.bucket

    def _sign_headers(self, method, resource_path, query, body,
                      extra_headers):
        payload_sha = hashlib.sha256(body).hexdigest() if body \
            else _EMPTY_SHA256
        return sigv4_headers(method, self._host_header, resource_path,
                             query, payload_sha, self.config,
                             extra_headers=extra_headers)

    # --- Storage impl ----------------------------------------------------
    def put(self, path: str, payload: bytes) -> None:
        status, _, data = self._request("PUT", self._key(path), body=payload)
        self._check(status, data, "PUT", path)

    def bulk_delete(self, paths: Iterable[str]) -> None:
        """Multi-object delete (`POST /?delete`), 1000 keys per request —
        the reference's `bulk_delete` batches identically."""
        paths = list(paths)
        for i in range(0, len(paths), 1000):
            chunk = paths[i:i + 1000]
            objects = "".join(
                f"<Object><Key>{self._escape(self._key(p))}</Key></Object>"
                for p in chunk)
            body = (f"<Delete><Quiet>true</Quiet>{objects}</Delete>"
                    ).encode()
            content_md5 = self._content_md5(body)
            status, _, data = self._request(
                "POST", "", query=[("delete", "")], body=body,
                extra_headers={"content-md5": content_md5})
            self._check(status, data, "POST ?delete", f"{len(chunk)} keys")
            # quiet mode: body only contains <Error> entries
            if b"<Error>" in data:
                root = ET.fromstring(data)
                errors = [e.findtext(f"{_NS}Key") or e.findtext("Key")
                          for e in root.iter() if e.tag.endswith("Error")]
                errors = [e for e in errors if e]
                if errors:
                    raise StorageError(f"bulk delete failed for {errors}")

    @staticmethod
    def _escape(text: str) -> str:
        return (text.replace("&", "&amp;").replace("<", "&lt;")
                .replace(">", "&gt;"))

    @staticmethod
    def _content_md5(body: bytes) -> str:
        import base64
        return base64.b64encode(hashlib.md5(body).digest()).decode()

    def list_files(self) -> list[str]:
        """ListObjectsV2 with pagination; returns keys relative to the
        prefix (the resolver roots each index at its own prefix)."""
        out: list[str] = []
        token: Optional[str] = None
        prefix = f"{self.prefix}/" if self.prefix else ""
        while True:
            query = [("list-type", "2"), ("prefix", prefix),
                     ("max-keys", "1000")]
            if token:
                query.append(("continuation-token", token))
            status, _, data = self._request("GET", "", query=query)
            self._check(status, data, "LIST", prefix)
            root = ET.fromstring(data)
            for contents in (list(root.iter(f"{_NS}Contents"))
                             or list(root.iter("Contents"))):
                key = (contents.findtext(f"{_NS}Key")
                       or contents.findtext("Key") or "")
                if key and not key.endswith("/"):
                    out.append(key[len(prefix):])
            token = (root.findtext(f"{_NS}NextContinuationToken")
                     or root.findtext("NextContinuationToken"))
            truncated = (root.findtext(f"{_NS}IsTruncated")
                         or root.findtext("IsTruncated"))
            if truncated != "true" or not token:
                break
        return sorted(out)
