from .base import Storage, StorageError, StorageResolver
from .local import LocalFileStorage
from .ram import RamStorage
from .cache import ByteRangeCache, MemorySizedCache, CachingStorage
from .s3 import S3CompatibleStorage, S3Config
from .azure import AzureBlobStorage, AzureConfig
from .gcs import GcsStorage
from .wrappers import (CountingStorage, DebouncedStorage,
                       StorageTimeoutPolicy, TimeoutAndRetryStorage)

__all__ = [
    "Storage", "StorageError", "StorageResolver", "LocalFileStorage",
    "RamStorage", "ByteRangeCache", "MemorySizedCache", "CachingStorage",
    "S3CompatibleStorage", "S3Config", "AzureBlobStorage", "AzureConfig",
    "GcsStorage", "CountingStorage",
    "DebouncedStorage", "StorageTimeoutPolicy", "TimeoutAndRetryStorage",
]
