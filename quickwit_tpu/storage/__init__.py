from .base import Storage, StorageError, StorageResolver
from .local import LocalFileStorage
from .ram import RamStorage
from .cache import ByteRangeCache, MemorySizedCache, CachingStorage

__all__ = [
    "Storage", "StorageError", "StorageResolver", "LocalFileStorage",
    "RamStorage", "ByteRangeCache", "MemorySizedCache", "CachingStorage",
]
