"""Azure Blob Storage backend on the stdlib HTTP client.

Role of the reference's `quickwit-storage/src/object_storage/
azure_blob_storage.rs:1` (azure_storage_blobs SDK there); this build has
no Azure SDK, so the Blob service REST API is implemented directly —
Put/Get(Range)/Delete/Head Blob + List Blobs — with real **SharedKey**
request signing (HMAC-SHA256 over the canonicalized headers/resource,
the same scheme the SDK computes).

URI shape: `azure://container/prefix`; the storage account + access key
resolve from config or the standard environment variables
(AZURE_STORAGE_ACCOUNT / AZURE_STORAGE_ACCESS_KEY), with an endpoint
override (QW_AZURE_ENDPOINT) for non-public clouds and the wire fake.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import time
import urllib.parse
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional

from ..common.uri import Uri
from .base import StorageError
from .http_object import HttpObjectStorage

_API_VERSION = "2021-08-06"


@dataclass
class AzureConfig:
    account: str = ""
    access_key: str = ""          # base64, as the portal hands it out
    endpoint: str = ""            # "" = https://{account}.blob.core.windows.net
    request_timeout_secs: float = 30.0

    @staticmethod
    def from_env(env: Optional[dict] = None) -> "AzureConfig":
        env = env if env is not None else os.environ
        return AzureConfig(
            account=env.get("AZURE_STORAGE_ACCOUNT", ""),
            access_key=env.get("AZURE_STORAGE_ACCESS_KEY", ""),
            endpoint=env.get("QW_AZURE_ENDPOINT", ""),
        )


def _rfc1123_now() -> str:
    return time.strftime("%a, %d %b %Y %H:%M:%S GMT", time.gmtime())


def shared_key_signature(access_key_b64: str, string_to_sign: str) -> str:
    key = base64.b64decode(access_key_b64)
    mac = hmac.new(key, string_to_sign.encode("utf-8"), hashlib.sha256)
    return base64.b64encode(mac.digest()).decode()


def shared_key_string_to_sign(method: str, headers: dict[str, str],
                              account: str, resource_path: str,
                              query: list[tuple[str, str]]) -> str:
    """The Blob-service SharedKey canonicalization (2015+ rules:
    Content-Length canonicalizes to "" when zero). Exposed so the wire
    fake verifies signatures with the identical computation."""
    def h(name: str) -> str:
        return headers.get(name, "")

    content_length = h("content-length")
    if content_length == "0":
        content_length = ""
    canonical_headers = "".join(
        f"{name}:{headers[name].strip()}\n"
        for name in sorted(headers) if name.startswith("x-ms-"))
    canonical_resource = f"/{account}{resource_path}"
    for name, value in sorted(query):
        canonical_resource += f"\n{name}:{value}"
    return "\n".join([
        method,
        h("content-encoding"), h("content-language"), content_length,
        h("content-md5"), h("content-type"), h("date"),
        h("if-modified-since"), h("if-match"), h("if-none-match"),
        h("if-unmodified-since"), h("range"),
    ]) + "\n" + canonical_headers + canonical_resource


class AzureBlobStorage(HttpObjectStorage):
    """`Storage` over the Azure Blob REST API. URI:
    `azure://container/prefix`. Connection pool, retry loop, and read
    paths live in HttpObjectStorage; this class adds SharedKey signing
    and Blob-specific operations."""

    service_name = "azure"

    def __init__(self, uri: Uri, config: Optional[AzureConfig] = None):
        self.config = config or AzureConfig.from_env()
        super().__init__(uri, self.config.request_timeout_secs)
        if not self.config.account or not self.config.access_key:
            raise StorageError(
                "azure storage requires AZURE_STORAGE_ACCOUNT and "
                "AZURE_STORAGE_ACCESS_KEY", kind="unauthorized")
        parts = uri.path.lstrip("/").split("/", 1)
        self.container = parts[0]
        self.prefix = parts[1].strip("/") if len(parts) > 1 else ""
        if not self.container:
            raise StorageError(f"azure uri has no container: {uri}")
        self._init_endpoint(
            self.config.endpoint or
            f"https://{self.config.account}.blob.core.windows.net")

    @property
    def _root_segment(self) -> str:
        return self.container

    def _sign_headers(self, method, resource_path, query, body,
                      extra_headers):
        headers = {
            "host": self._host_header,
            "x-ms-date": _rfc1123_now(),
            "x-ms-version": _API_VERSION,
        }
        if body:
            headers["content-length"] = str(len(body))
        if extra_headers:
            headers.update({k.lower(): v for k, v in extra_headers.items()})
        signature = shared_key_signature(
            self.config.access_key,
            shared_key_string_to_sign(method, headers, self.config.account,
                                      resource_path, query))
        headers["Authorization"] = \
            f"SharedKey {self.config.account}:{signature}"
        return headers

    # --- Storage impl ----------------------------------------------------
    def put(self, path: str, payload: bytes) -> None:
        status, _, data = self._request(
            "PUT", self._key(path), body=payload,
            extra_headers={"x-ms-blob-type": "BlockBlob"})
        self._check(status, data, "PUT", path)

    def list_files(self) -> list[str]:
        """List Blobs (`?restype=container&comp=list`) with pagination;
        names are relative to the prefix."""
        out: list[str] = []
        marker = ""
        while True:
            query = [("comp", "list"), ("restype", "container")]
            if self.prefix:
                query.append(("prefix", self.prefix + "/"))
            if marker:
                query.append(("marker", marker))
            status, _, data = self._request("GET", "", query=query)
            self._check(status, data, "LIST", self.container)
            root = ET.fromstring(data)
            for blob in root.iter("Blob"):
                name = blob.findtext("Name") or ""
                if self.prefix:
                    name = name[len(self.prefix) + 1:]
                if name and not name.endswith("/"):
                    # '/'-suffixed zero-byte blobs are directory
                    # placeholders (Storage Explorer / ADLS), not objects
                    out.append(name)
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return sorted(out)
