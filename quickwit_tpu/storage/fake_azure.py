"""Wire-accurate in-process Azure Blob fake for tests (the Azurite
role). Speaks the Blob REST subset the backend uses — Put/Get(Range)/
Delete/Head Blob, List Blobs with markers — and VERIFIES SharedKey
signatures with the identical canonicalization the real service applies
(shared_key_string_to_sign from storage/azure.py), so the signing path
is tested end to end."""

from __future__ import annotations

import hmac
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .azure import shared_key_signature, shared_key_string_to_sign


class FakeAzureServer:
    def __init__(self, account: str = "devacct", access_key: str = ""):
        self.account = account
        self.access_key = access_key  # base64; "" disables verification
        # container -> blob name -> bytes
        self.blobs: dict[str, dict[str, bytes]] = {}
        # qwlint: disable-next-line=QW008 - storage base/fakes leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self.lock = threading.Lock()
        self.request_log: list[tuple[str, str]] = []
        self.auth_failures = 0
        self.fail_requests = 0
        self.list_page_size: Optional[int] = None  # force pagination
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # noqa: D102 - silence
                pass

            def _reply(self, status: int, body: bytes = b"",
                       headers: Optional[dict] = None) -> None:
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _parts(self):
                parsed = urllib.parse.urlparse(self.path)
                query = urllib.parse.parse_qsl(parsed.query,
                                               keep_blank_values=True)
                segments = urllib.parse.unquote(
                    parsed.path).lstrip("/").split("/", 1)
                container = segments[0]
                blob = segments[1] if len(segments) > 1 else ""
                return parsed, container, blob, query

            def _check_auth(self, resource_path, query) -> bool:
                if not server.access_key:
                    return True
                auth = self.headers.get("Authorization", "")
                if not auth.startswith(f"SharedKey {server.account}:"):
                    return False
                presented = auth.rsplit(":", 1)[1]
                headers = {k.lower(): v for k, v in self.headers.items()}
                expected = shared_key_signature(
                    server.access_key,
                    shared_key_string_to_sign(
                        self.command, headers, server.account,
                        resource_path, list(query)))
                if not hmac.compare_digest(expected, presented):
                    server.auth_failures += 1
                    return False
                return True

            def _common(self):
                parsed, container, blob, query = self._parts()
                # ALWAYS consume the body first: replying 500/403 without
                # reading it would desync the keep-alive stream and make
                # the client's retry parse stale bytes as a request line
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                with server.lock:
                    server.request_log.append((self.command, parsed.path))
                    if server.fail_requests > 0:
                        server.fail_requests -= 1
                        self._reply(500, b"<Error>boom</Error>")
                        return None
                resource_path = "/" + urllib.parse.quote(
                    f"{container}/{blob}" if blob else container,
                    safe="/-_.~")
                if not self._check_auth(resource_path, query):
                    self._reply(403, b"<Error>AuthenticationFailed</Error>")
                    return None
                return container, blob, dict(query), body

            def do_PUT(self):  # noqa: N802
                common = self._common()
                if common is None:
                    return
                container, blob, _query, body = common
                if self.headers.get("x-ms-blob-type") != "BlockBlob":
                    return self._reply(400, b"<Error>MissingBlobType</Error>")
                with server.lock:
                    server.blobs.setdefault(container, {})[blob] = body
                self._reply(201)

            def do_DELETE(self):  # noqa: N802
                common = self._common()
                if common is None:
                    return
                container, blob, _query, _body = common
                with server.lock:
                    existed = server.blobs.get(container, {}).pop(blob, None)
                self._reply(202 if existed is not None else 404)

            def do_HEAD(self):  # noqa: N802
                self._get_or_head()

            def do_GET(self):  # noqa: N802
                self._get_or_head()

            def _get_or_head(self):
                common = self._common()
                if common is None:
                    return
                container, blob, query, _body = common
                if query.get("comp") == "list":
                    return self._list(container, query)
                with server.lock:
                    data = server.blobs.get(container, {}).get(blob)
                if data is None:
                    return self._reply(404, b"<Error>BlobNotFound</Error>")
                range_header = self.headers.get("Range") or \
                    self.headers.get("x-ms-range")
                if range_header and range_header.startswith("bytes="):
                    lo, _, hi = range_header[6:].partition("-")
                    start = int(lo)
                    end = int(hi) + 1 if hi else len(data)
                    if start >= len(data):
                        return self._reply(416)
                    chunk = data[start:min(end, len(data))]
                    return self._reply(206, chunk, {
                        "Content-Range":
                            f"bytes {start}-{start + len(chunk) - 1}"
                            f"/{len(data)}"})
                self._reply(200, data)

            def _list(self, container: str, query: dict) -> None:
                prefix = query.get("prefix", "")
                marker = query.get("marker", "")
                with server.lock:
                    names = sorted(n for n in
                                   server.blobs.get(container, {})
                                   if n.startswith(prefix))
                if marker:
                    names = [n for n in names if n > marker]
                next_marker = ""
                if server.list_page_size is not None \
                        and len(names) > server.list_page_size:
                    names = names[: server.list_page_size]
                    next_marker = names[-1]
                blobs_xml = "".join(
                    f"<Blob><Name>{n}</Name></Blob>" for n in names)
                body = (f"<?xml version=\"1.0\"?><EnumerationResults>"
                        f"<Blobs>{blobs_xml}</Blobs>"
                        f"<NextMarker>{next_marker}</NextMarker>"
                        f"</EnumerationResults>").encode()
                self._reply(200, body,
                            {"Content-Type": "application/xml"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self._httpd.server_port}"

    def start(self) -> "FakeAzureServer":
        # qwlint: disable-next-line=QW003 - test-double HTTP server; no
        # query context exists on this path
        # qwlint: disable-next-line=QW008 - storage base/fakes leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
