"""Shared machinery for HTTP object-storage backends (S3, Azure, GCS).

One copy of the per-thread connection pool, the bounded retry loop with
exponential backoff and connection recycling, path validation, status→
StorageError mapping, and the ranged/whole-object read paths — the
backends differ only in how a request is SIGNED (`_sign_headers`) and in
service-specific operations (put headers, delete semantics, listing)."""

from __future__ import annotations

import http.client
import socket
import threading
import time
import urllib.parse
from typing import Optional

from ..common.uri import Uri
from .base import Storage, StorageError

_RETRYABLE_STATUS = (500, 502, 503, 504)
_MAX_ATTEMPTS = 3


class HttpObjectStorage(Storage):
    """Base for storage backends speaking HTTP to an object service.
    Subclasses set `service_name`, `_root_segment` (bucket/container),
    `prefix`, endpoint fields via `_init_endpoint`, and implement
    `_sign_headers`."""

    service_name = "object"

    def __init__(self, uri: Uri, timeout_secs: float):
        super().__init__(uri)
        self._timeout_secs = timeout_secs
        self._local = threading.local()

    def _init_endpoint(self, endpoint: str) -> None:
        parsed = urllib.parse.urlparse(endpoint)
        self._secure = parsed.scheme == "https"
        self._host = parsed.hostname or ""
        self._port = parsed.port or (443 if self._secure else 80)
        self._host_header = parsed.netloc

    # --- connection pool (one per thread) ------------------------------
    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self._secure
                   else http.client.HTTPConnection)
            conn = cls(self._host, self._port, timeout=self._timeout_secs)
            self._local.conn = conn
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    # --- shared request plumbing ----------------------------------------
    def _key(self, path: str) -> str:
        if path.startswith("/") or ".." in path.split("/"):
            raise StorageError(f"invalid object path: {path!r}")
        return f"{self.prefix}/{path}" if self.prefix else path

    def _sign_headers(self, method: str, resource_path: str,
                      query: list[tuple[str, str]], body: bytes,
                      extra_headers: Optional[dict[str, str]]
                      ) -> dict[str, str]:
        raise NotImplementedError

    def _resource_path(self, key: str) -> str:
        root = self._root_segment
        return "/" + urllib.parse.quote(
            f"{root}/{key}" if key else root, safe="/-_.~")

    def _request(self, method: str, key: str,
                 query: Optional[list[tuple[str, str]]] = None,
                 body: bytes = b"",
                 extra_headers: Optional[dict[str, str]] = None
                 ) -> tuple[int, dict[str, str], bytes]:
        query = query or []
        resource_path = self._resource_path(key)
        last_error: Optional[Exception] = None
        for attempt in range(_MAX_ATTEMPTS):
            headers = self._sign_headers(method, resource_path, query,
                                         body, extra_headers)
            target = resource_path
            if query:
                target += "?" + urllib.parse.urlencode(sorted(query))
            try:
                conn = self._connection()
                conn.request(method, target, body=body or None,
                             headers=headers)
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
                resp_headers = {k.lower(): v for k, v in resp.getheaders()}
            except (OSError, http.client.HTTPException,
                    socket.timeout) as exc:
                self._drop_connection()
                last_error = exc
                time.sleep(0.05 * (2 ** attempt))
                continue
            if status in _RETRYABLE_STATUS:
                last_error = StorageError(
                    f"{self.service_name} {method} {key}: HTTP {status}",
                    kind="internal")
                time.sleep(0.05 * (2 ** attempt))
                continue
            return status, resp_headers, data
        raise StorageError(
            f"{self.service_name} {method} {key} failed after "
            f"{_MAX_ATTEMPTS} attempts: {last_error}",
            kind="timeout" if isinstance(last_error, socket.timeout)
            else "internal")

    def _check(self, status: int, data: bytes, op: str, path: str) -> None:
        if status == 404:
            raise StorageError(f"not found: {path}", kind="not_found")
        if status in (401, 403):
            raise StorageError(
                f"{self.service_name} {op} {path}: HTTP {status}",
                kind="unauthorized")
        if status >= 300:
            raise StorageError(
                f"{self.service_name} {op} {path}: HTTP {status}: "
                f"{data[:200]!r}")

    # --- shared Storage operations ---------------------------------------
    def delete(self, path: str) -> None:
        status, _, data = self._request("DELETE", self._key(path))
        # object DELETEs are idempotent server-side: a 404 means a racing
        # GC already won, but the reference surfaces not_found for single
        # deletes
        if status == 404:
            raise StorageError(f"not found: {path}", kind="not_found")
        self._check(status, data, "DELETE", path)

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        if start >= end:
            return b""
        status, _, data = self._request(
            "GET", self._key(path),
            extra_headers={"range": f"bytes={start}-{end - 1}"})
        if status == 416:
            raise StorageError(
                f"range {start}:{end} out of bounds for {path}")
        self._check(status, data, "GET", path)
        if status == 200 and (start > 0 or len(data) > end - start):
            # 200 (not 206) means the server ignored the Range header and
            # returned the full object; slice host-side
            return data[start:end]
        return data

    def get_all(self, path: str) -> bytes:
        status, _, data = self._request("GET", self._key(path))
        self._check(status, data, "GET", path)
        return data

    def file_num_bytes(self, path: str) -> int:
        status, headers, data = self._request("HEAD", self._key(path))
        self._check(status, data, "HEAD", path)
        return int(headers.get("content-length", 0))
