"""Storage wrappers: tail-latency hedging, debouncing, IO counting.

Roles of the reference's `quickwit-storage` proxies:

- `TimeoutAndRetryStorage` (`timeout_and_retry_storage.rs:1`): S3 tail
  latency is long-tailed; AWS's own guidance is to retry aggressively
  rather than wait. Each `get_slice` attempt gets a deadline of
  `timeout + num_bytes / min_throughput`; on deadline a **hedge**
  request is launched while the first keeps running (strictly better
  than the reference's abort-and-retry, which its own TODO #5468 calls
  out) — whichever attempt finishes first wins.
- `DebouncedStorage` (`debouncer.rs:1`): concurrent identical GETs
  (e.g. two queries warming the same hotcache) share one underlying
  fetch.
- `CountingStorage` (`counting_storage.rs:1`): per-operation counters
  for tests and the IO metrics surface.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Iterable, Iterator

from ..common.ctx import run_with_context
from ..common.deadline import DEADLINE_ERROR_MARK, current_deadline
from .base import Storage, StorageError


@dataclass
class StorageTimeoutPolicy:
    """Per-attempt deadline for ranged reads (reference:
    `node_config/mod.rs:612` — same defaults)."""
    min_throughput_bytes_per_sec: int = 100_000
    timeout_millis: int = 2_000
    max_num_retries: int = 1

    def attempt_timeouts(self, num_bytes: int) -> Iterator[float]:
        floor = (num_bytes / self.min_throughput_bytes_per_sec
                 if self.min_throughput_bytes_per_sec else 0.0)
        timeout = self.timeout_millis / 1000.0 + floor
        for _ in range(self.max_num_retries + 1):
            yield timeout


class TimeoutAndRetryStorage(Storage):
    """Hedged ranged reads: a slow attempt is raced against a fresh one
    instead of waited on; a failed attempt consumes the retry budget while
    in-flight hedges keep racing. Attempts run on dedicated threads (not a
    bounded pool) so wedged requests cannot starve later reads into
    spurious timeouts — the wrapper fronts network storage, where request
    latency dwarfs thread spawn cost."""

    def __init__(self, underlying: Storage,
                 policy: StorageTimeoutPolicy | None = None):
        super().__init__(underlying.uri)
        self.underlying = underlying
        self.policy = policy or StorageTimeoutPolicy()

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        results: "queue.Queue[tuple[bool, object]]" = queue.Queue()

        def attempt() -> None:
            try:
                results.put((True, self.underlying.get_slice(path, start,
                                                             end)))
            # qwlint: disable-next-line=QW004 - every attempt's error is
            # shipped across the queue and re-raised by the racing caller
            except Exception as exc:  # noqa: BLE001 - raced; re-raised below
                results.put((False, exc))

        def launch() -> None:
            # the hedge thread must see the query's deadline/tenant so the
            # underlying storage (fault injection, rate accounting) attributes
            # the read to the right query instead of an ambient default
            # qwlint: disable-next-line=QW008 - hedge attempts rendezvous
            # through queue.Queue, which the qwrace scheduler cannot
            # instrument; gating only the thread would stall the gated
            # schedule on an invisible queue.get, so the whole hedge path
            # stays on raw primitives (leaf machinery, no seam locks held)
            threading.Thread(target=run_with_context(attempt),
                             name="storage-hedge", daemon=True).start()

        timeouts = list(self.policy.attempt_timeouts(end - start))
        max_attempts = len(timeouts)
        per_attempt_timeout = timeouts[0]
        # hedge waits never extend past the query's own budget: a read the
        # caller can no longer use must fail now, not at the policy timeout
        query_deadline = current_deadline()
        launched, failed = 1, 0
        last_error: Exception | None = None
        launch()
        while True:
            wait_timeout = per_attempt_timeout
            if query_deadline is not None and query_deadline.bounded:
                if query_deadline.expired:
                    raise StorageError(
                        f"get_slice {path}[{start}:{end}] "
                        f"{DEADLINE_ERROR_MARK}", kind="deadline")
                wait_timeout = min(wait_timeout,
                                   max(query_deadline.remaining(), 0.001))
            try:
                ok, value = results.get(timeout=wait_timeout)
            except queue.Empty:
                if query_deadline is not None and query_deadline.expired:
                    raise StorageError(
                        f"get_slice {path}[{start}:{end}] "
                        f"{DEADLINE_ERROR_MARK} after {launched} attempts",
                        kind="deadline")
                if launched < max_attempts:
                    launch()  # hedge: race a fresh attempt, keep waiting
                    launched += 1
                    # hedged retries are the tail-latency signal the
                    # profile's storage counters must carry
                    from ..observability.profile import profile_add
                    profile_add("storage_hedged_requests")
                    continue
                raise StorageError(
                    f"get_slice {path}[{start}:{end}] timed out after "
                    f"{launched} hedged attempts", kind="timeout")
            if ok:
                return value  # type: ignore[return-value]
            failed += 1
            last_error = value  # type: ignore[assignment]
            if launched < max_attempts:
                launch()  # a failure consumes the retry budget too
                launched += 1
                from ..observability.profile import profile_add
                profile_add("storage_hedged_requests")
                continue
            if failed >= launched:
                raise last_error  # every attempt has failed
            # budget exhausted but attempts are still in flight: keep waiting

    # non-latency-critical operations pass through
    def put(self, path: str, payload: bytes) -> None:
        self.underlying.put(path, payload)

    def delete(self, path: str) -> None:
        self.underlying.delete(path)

    def bulk_delete(self, paths: Iterable[str]) -> None:
        self.underlying.bulk_delete(paths)

    def get_all(self, path: str) -> bytes:
        return self.underlying.get_all(path)

    def file_num_bytes(self, path: str) -> int:
        return self.underlying.file_num_bytes(path)

    def list_files(self) -> list[str]:
        return self.underlying.list_files()


class DebouncedStorage(Storage):
    """Concurrent identical `get_slice` calls share one underlying fetch."""

    def __init__(self, underlying: Storage):
        super().__init__(underlying.uri)
        self.underlying = underlying
        # qwlint: disable-next-line=QW008 - leaf lock: the critical
        # sections are pure dict ops with no instrumented sync inside, so
        # under the gated scheduler the lock is never even contended
        self._lock = threading.Lock()
        self._inflight: dict[tuple, "_Cell"] = {}

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        key = (path, start, end)
        with self._lock:
            cell = self._inflight.get(key)
            if cell is None:
                cell = _Cell()
                self._inflight[key] = cell
                leader = True
            else:
                leader = False
        if leader:
            try:
                cell.value = self.underlying.get_slice(path, start, end)
            # qwlint: disable-next-line=QW004 - the error is published via
            # the cell and re-raised by the leader AND every waiter below
            except Exception as exc:  # noqa: BLE001 - published to waiters
                cell.error = exc
            finally:
                with self._lock:
                    self._inflight.pop(key, None)
                cell.done.set()
        else:
            cell.done.wait()
        if cell.error is not None:
            raise cell.error
        return cell.value  # type: ignore[return-value]

    def put(self, path: str, payload: bytes) -> None:
        self.underlying.put(path, payload)

    def delete(self, path: str) -> None:
        self.underlying.delete(path)

    def bulk_delete(self, paths: Iterable[str]) -> None:
        self.underlying.bulk_delete(paths)

    def get_all(self, path: str) -> bytes:
        return self.underlying.get_all(path)

    def file_num_bytes(self, path: str) -> int:
        return self.underlying.file_num_bytes(path)

    def list_files(self) -> list[str]:
        return self.underlying.list_files()


class _Cell:
    __slots__ = ("done", "value", "error")

    def __init__(self) -> None:
        # qwlint: disable-next-line=QW008 - paired with the raw hedge
        # machinery above (set by an uninstrumented leader thread)
        self.done = threading.Event()
        self.value: bytes | None = None
        self.error: Exception | None = None


@dataclass
class IOCounters:
    get_slice: int = 0
    get_slice_bytes: int = 0
    get_all: int = 0
    put: int = 0
    put_bytes: int = 0
    delete: int = 0
    # qwlint: disable-next-line=QW008 - leaf counter lock, no
    # instrumented ops inside its critical sections
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)


class CountingStorage(Storage):
    def __init__(self, underlying: Storage):
        super().__init__(underlying.uri)
        self.underlying = underlying
        self.counters = IOCounters()

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        data = self.underlying.get_slice(path, start, end)
        with self.counters._lock:
            self.counters.get_slice += 1
            self.counters.get_slice_bytes += len(data)
        return data

    def get_all(self, path: str) -> bytes:
        data = self.underlying.get_all(path)
        with self.counters._lock:
            self.counters.get_all += 1
        return data

    def put(self, path: str, payload: bytes) -> None:
        self.underlying.put(path, payload)
        with self.counters._lock:
            self.counters.put += 1
            self.counters.put_bytes += len(payload)

    def delete(self, path: str) -> None:
        self.underlying.delete(path)
        with self.counters._lock:
            self.counters.delete += 1

    def bulk_delete(self, paths: Iterable[str]) -> None:
        paths = list(paths)
        self.underlying.bulk_delete(paths)
        with self.counters._lock:
            self.counters.delete += len(paths)

    def file_num_bytes(self, path: str) -> int:
        return self.underlying.file_num_bytes(path)

    def list_files(self) -> list[str]:
        return self.underlying.list_files()
