"""Google Cloud Storage backend.

Role of the reference's `quickwit-storage/src/opendal_storage/` GCS
support. GCS's XML API implements the S3 wire protocol in its
"simple migration" interoperability mode — HMAC keys + AWS-SigV4-signed
requests against storage.googleapis.com — so the backend IS the proven
SigV4-on-stdlib S3 client pointed at the GCS endpoint, with GCS-specific
credential resolution (GCS_HMAC_KEY_ID / GCS_HMAC_SECRET, falling back
to the AWS variables some deployments reuse) and an endpoint override
(QW_GCS_ENDPOINT) for testing.

URI shape: `gs://bucket/prefix`.
"""

from __future__ import annotations

import os
from typing import Optional

from ..common.uri import Uri
from .s3 import S3CompatibleStorage, S3Config


def gcs_config_from_env(env: Optional[dict] = None) -> S3Config:
    env = env if env is not None else os.environ
    return S3Config(
        endpoint=env.get("QW_GCS_ENDPOINT",
                         "https://storage.googleapis.com"),
        # the scope region is not meaningful to GCS's interop mode, but
        # it participates in the SigV4 key derivation on both sides
        region=env.get("GCS_REGION", "auto"),
        access_key=env.get("GCS_HMAC_KEY_ID",
                           env.get("AWS_ACCESS_KEY_ID", "")),
        secret_key=env.get("GCS_HMAC_SECRET",
                           env.get("AWS_SECRET_ACCESS_KEY", "")),
    )


class GcsStorage(S3CompatibleStorage):
    """`Storage` over the GCS XML (S3-interop) API. URI:
    `gs://bucket/prefix`."""

    service_name = "gcs"

    def __init__(self, uri: Uri, config: Optional[S3Config] = None):
        super().__init__(uri, config or gcs_config_from_env())

    def bulk_delete(self, paths) -> None:
        # GCS's XML interop API has no S3 multi-object `POST /?delete`
        # (batching exists only in the JSON API) — per-object deletes
        from .base import Storage
        Storage.bulk_delete(self, paths)
