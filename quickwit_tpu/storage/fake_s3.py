"""In-process fake S3 server for tests and benchmarks.

Role of the reference's localstack/MinIO-backed integration tests
(`quickwit-integration-tests/.localstack/`, and the `s3` feature of
`quickwit-storage` tests): an HTTP server speaking enough of the S3 REST
API to exercise `S3CompatibleStorage` end-to-end — object GET (with
Range), PUT, HEAD, DELETE, multi-object delete, and ListObjectsV2 —
plus two test-harness features the real service obviously lacks:

- **latency injection** (`latency_secs`, or a `latency_fn(method, key)`)
  so warmup/compute pipelining has real storage latency to hide;
- **fault injection** (`fail_requests`) to test retry paths;
- a **request log** so tests can assert GET counts (e.g. the ≤2-GET
  split-open guarantee) and inspect ranges.

When constructed with credentials it *verifies* SigV4 signatures by
re-deriving them server-side — a genuine conformance check of the
client's signer, not just an echo.
"""

from __future__ import annotations

import hashlib
import hmac
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

_XMLNS = 'xmlns="http://s3.amazonaws.com/doc/2006-03-01/"'


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class FakeS3Server:
    def __init__(self, access_key: str = "", secret_key: str = "",
                 latency_secs: float = 0.0,
                 latency_fn: Optional[Callable[[str, str], float]] = None):
        self.access_key = access_key
        self.secret_key = secret_key
        self.latency_secs = latency_secs
        self.latency_fn = latency_fn
        self.objects: dict[str, dict[str, bytes]] = {}  # bucket -> key -> data
        self.ignore_range = False  # emulate servers that 200 full objects
        # qwlint: disable-next-line=QW008 - storage base/fakes leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self.lock = threading.Lock()
        self.request_log: list[tuple[str, str, dict]] = []
        self.fail_requests = 0        # fail the next N requests with 500
        self.auth_failures = 0        # count of rejected signatures
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):  # noqa: D102 - silence
                pass

            def _object_path(self) -> tuple[str, str, dict[str, list[str]]]:
                parsed = urllib.parse.urlparse(self.path)
                query = urllib.parse.parse_qs(parsed.query,
                                              keep_blank_values=True)
                parts = urllib.parse.unquote(parsed.path).lstrip("/")
                bucket, _, key = parts.partition("/")
                return bucket, key, query

            def _reply(self, status: int, body: bytes = b"",
                       headers: Optional[dict] = None) -> None:
                self.send_response(status)
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _check_auth(self, body: bytes) -> bool:
                if not server.secret_key:
                    return True
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256 "):
                    return False
                try:
                    fields = dict(
                        part.strip().split("=", 1)
                        for part in auth[len("AWS4-HMAC-SHA256 "):].split(","))
                    credential = fields["Credential"]
                    signed_headers = fields["SignedHeaders"]
                    signature = fields["Signature"]
                    _akid, datestamp, region, service, _term = \
                        credential.split("/")
                except (KeyError, ValueError):
                    return False
                parsed = urllib.parse.urlparse(self.path)
                query_pairs = urllib.parse.parse_qsl(
                    parsed.query, keep_blank_values=True)
                canonical_query = "&".join(
                    f"{urllib.parse.quote(k, safe='-_.~')}="
                    f"{urllib.parse.quote(v, safe='-_.~')}"
                    for k, v in sorted(query_pairs))
                names = signed_headers.split(";")
                canonical_headers = "".join(
                    f"{n}:{(self.headers.get(n) or '').strip()}\n"
                    for n in names)
                payload_sha = self.headers.get("x-amz-content-sha256",
                                               hashlib.sha256(b"").hexdigest())
                canonical_request = "\n".join([
                    self.command, urllib.parse.quote(
                        urllib.parse.unquote(parsed.path), safe="/-_.~"),
                    canonical_query, canonical_headers, signed_headers,
                    payload_sha])
                scope = f"{datestamp}/{region}/{service}/aws4_request"
                string_to_sign = "\n".join([
                    "AWS4-HMAC-SHA256",
                    self.headers.get("x-amz-date", ""), scope,
                    hashlib.sha256(canonical_request.encode()).hexdigest()])
                key = _sign(f"AWS4{server.secret_key}".encode(), datestamp)
                key = _sign(key, region)
                key = _sign(key, service)
                key = _sign(key, "aws4_request")
                expected = hmac.new(key, string_to_sign.encode(),
                                    hashlib.sha256).hexdigest()
                if not hmac.compare_digest(expected, signature):
                    server.auth_failures += 1
                    return False
                # integrity: payload hash must match the body we received
                if body and hashlib.sha256(body).hexdigest() != payload_sha:
                    server.auth_failures += 1
                    return False
                return True

            def _common(self) -> Optional[tuple[str, str, dict, bytes]]:
                bucket, key, query = self._object_path()
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                with server.lock:
                    server.request_log.append(
                        (self.command, f"{bucket}/{key}",
                         {k.lower(): v for k, v in self.headers.items()}))
                    if server.fail_requests > 0:
                        server.fail_requests -= 1
                        self._reply(500, b"<Error>injected</Error>")
                        return None
                delay = (server.latency_fn(self.command, key)
                         if server.latency_fn else server.latency_secs)
                if delay:
                    time.sleep(delay)
                if not self._check_auth(body):
                    self._reply(403, b"<Error>SignatureDoesNotMatch</Error>")
                    return None
                return bucket, key, query, body

            # --- verbs -------------------------------------------------
            def do_PUT(self):
                common = self._common()
                if common is None:
                    return
                bucket, key, _, body = common
                with server.lock:
                    server.objects.setdefault(bucket, {})[key] = body
                self._reply(200)

            def do_HEAD(self):
                common = self._common()
                if common is None:
                    return
                bucket, key, _, _ = common
                with server.lock:
                    data = server.objects.get(bucket, {}).get(key)
                if data is None:
                    # HEAD responses carry no body
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()

            def do_GET(self):
                common = self._common()
                if common is None:
                    return
                bucket, key, query, _ = common
                if not key and "list-type" in query:
                    return self._list(bucket, query)
                with server.lock:
                    data = server.objects.get(bucket, {}).get(key)
                if data is None:
                    return self._reply(404, b"<Error>NoSuchKey</Error>")
                range_header = self.headers.get("Range")
                if server.ignore_range:
                    range_header = None
                if range_header and range_header.startswith("bytes="):
                    spec = range_header[len("bytes="):]
                    start_s, _, end_s = spec.partition("-")
                    if start_s == "":  # suffix range: last N bytes
                        start = max(0, len(data) - int(end_s))
                        end = len(data)
                    else:
                        start = int(start_s)
                        end = min(int(end_s) + 1 if end_s else len(data),
                                  len(data))
                    if start >= len(data):
                        return self._reply(416)
                    chunk = data[start:end]
                    return self._reply(
                        206, chunk,
                        {"Content-Range":
                         f"bytes {start}-{end - 1}/{len(data)}"})
                self._reply(200, data)

            def _list(self, bucket: str, query: dict) -> None:
                prefix = (query.get("prefix") or [""])[0]
                max_keys = int((query.get("max-keys") or ["1000"])[0])
                token = (query.get("continuation-token") or [""])[0]
                with server.lock:
                    keys = sorted(k for k in server.objects.get(bucket, {})
                                  if k.startswith(prefix))
                if token:
                    keys = [k for k in keys if k > token]
                page, rest = keys[:max_keys], keys[max_keys:]
                contents = "".join(
                    f"<Contents><Key>{k}</Key></Contents>" for k in page)
                truncated = "true" if rest else "false"
                next_token = (f"<NextContinuationToken>{page[-1]}"
                              "</NextContinuationToken>") if rest else ""
                body = (f'<ListBucketResult {_XMLNS}>'
                        f"<IsTruncated>{truncated}</IsTruncated>"
                        f"{next_token}{contents}</ListBucketResult>").encode()
                self._reply(200, body)

            def do_DELETE(self):
                common = self._common()
                if common is None:
                    return
                bucket, key, _, _ = common
                with server.lock:
                    existed = server.objects.get(bucket, {}).pop(key, None)
                if existed is None:
                    return self._reply(404, b"<Error>NoSuchKey</Error>")
                self._reply(204)

            def do_POST(self):
                common = self._common()
                if common is None:
                    return
                bucket, _, query, body = common
                if "delete" not in query:
                    return self._reply(400, b"<Error>unsupported</Error>")
                import xml.etree.ElementTree as ET
                root = ET.fromstring(body)
                deleted = []
                with server.lock:
                    for obj in root.iter("Object"):
                        key = obj.findtext("Key") or ""
                        server.objects.get(bucket, {}).pop(key, None)
                        deleted.append(key)
                self._reply(200, (f'<DeleteResult {_XMLNS}>'
                                  "</DeleteResult>").encode())

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        # qwlint: disable-next-line=QW003 - test-double HTTP server; no
        # query context exists on this path
        # qwlint: disable-next-line=QW008 - storage base/fakes leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-s3", daemon=True)

    # --- lifecycle -------------------------------------------------------
    def start(self) -> "FakeS3Server":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "FakeS3Server":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # --- test helpers ----------------------------------------------------
    def get_requests(self, method: Optional[str] = None
                     ) -> list[tuple[str, str, dict]]:
        with self.lock:
            log = list(self.request_log)
        return [r for r in log if method is None or r[0] == method]

    def clear_log(self) -> None:
        with self.lock:
            self.request_log.clear()
