"""Disk-resident split cache for searchers.

Role of the reference's `SearchSplitCache` + `SplitTable`
(`quickwit-storage/src/split_cache/mod.rs:43`, `split_table.rs:1`,
`download_task.rs`): leaf requests REPORT the splits they touch; a
download worker copies the hottest candidates from object storage into a
local directory as whole `.split` files; the reader open path serves
cached splits from local disk, making cold-split economics against S3
viable. An in-memory eviction table tracks candidate / downloading /
on-disk statuses under byte + count budgets with LRU-by-touch eviction
(most-recently-reported candidates download first, least-recently-touched
on-disk splits evict first).

Crash safety mirrors the reference: downloads write `<id>.split.temp`
then rename; leftover `.temp` files are deleted on startup; `.split`
files found on startup are adopted into the table.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Optional

from ..observability.metrics import METRICS

logger = logging.getLogger(__name__)

_HITS = METRICS.counter("qw_split_cache_hits_total",
                        "reader opens served from the disk split cache")
_MISSES = METRICS.counter("qw_split_cache_misses_total",
                          "reader opens that had to go to object storage")
_EVICTIONS = METRICS.counter("qw_split_cache_evictions_total",
                             "splits evicted from the disk cache")
_DOWNLOADS = METRICS.counter("qw_split_cache_downloads_total",
                             "splits downloaded into the disk cache")

CANDIDATE = "candidate"
DOWNLOADING = "downloading"
ON_DISK = "on_disk"


class SplitTable:
    """Eviction table (split_table.rs role): every known split is
    candidate, downloading, or on-disk; a monotonic touch counter orders
    both download priority (newest candidate first) and eviction (oldest
    on-disk first). NOT thread-safe — the cache holds the lock."""

    def __init__(self, max_bytes: int, max_splits: int = 10_000):
        self.max_bytes = max_bytes
        self.max_splits = max_splits
        self._splits: dict[str, dict[str, Any]] = {}
        self._touch_counter = 0
        self.on_disk_bytes = 0

    def _touch_stamp(self) -> int:
        self._touch_counter += 1
        return self._touch_counter

    def info(self, split_id: str) -> Optional[dict[str, Any]]:
        return self._splits.get(split_id)

    def touch(self, split_id: str, storage_uri: str = "",
              num_bytes_hint: int = 0) -> None:
        """Report one split as interesting (a leaf request touched it).
        Unknown splits enter as candidates."""
        info = self._splits.get(split_id)
        if info is None:
            self._splits[split_id] = {
                "status": CANDIDATE, "storage_uri": storage_uri,
                "num_bytes": num_bytes_hint, "touch": self._touch_stamp()}
        else:
            info["touch"] = self._touch_stamp()

    def register_on_disk(self, split_id: str, num_bytes: int,
                         storage_uri: str = "") -> None:
        info = self._splits.get(split_id)
        if info is not None and info["status"] == ON_DISK:
            return
        if info is not None and info.get("reserved"):
            # converting a make_room reservation into the real entry:
            # release the reserved bytes before adding the actual size
            self.on_disk_bytes -= info["num_bytes"]
        self._splits[split_id] = {
            "status": ON_DISK, "storage_uri": storage_uri,
            "num_bytes": num_bytes, "touch": self._touch_stamp()}
        self.on_disk_bytes += num_bytes

    def forget(self, split_id: str) -> None:
        info = self._splits.pop(split_id, None)
        if info is not None and (info["status"] == ON_DISK
                                 or info.get("reserved")):
            self.on_disk_bytes -= info["num_bytes"]

    def num_on_disk(self) -> int:
        return sum(1 for i in self._splits.values()
                   if i["status"] == ON_DISK)

    def best_candidate(self) -> Optional[tuple[str, str]]:
        """(split_id, storage_uri) of the most-recently-touched candidate,
        or None. The freshest report downloads first — cold candidates age
        out of priority naturally."""
        best = None
        for split_id, info in self._splits.items():
            if info["status"] != CANDIDATE:
                continue
            if best is None or info["touch"] > best[2]:
                best = (split_id, info["storage_uri"], info["touch"])
        return (best[0], best[1]) if best else None

    def start_download(self, split_id: str) -> None:
        info = self._splits.get(split_id)
        if info is not None:
            info["status"] = DOWNLOADING

    def abort_download(self, split_id: str) -> None:
        info = self._splits.get(split_id)
        if info is not None and info["status"] == DOWNLOADING:
            if info.pop("reserved", None):
                self.on_disk_bytes -= info["num_bytes"]
            info["status"] = CANDIDATE

    def make_room(self, incoming_bytes: int,
                  incoming_count: int = 1,
                  reserve_for: Optional[str] = None) -> "Optional[list[str]]":
        """Evict least-recently-touched ON-DISK splits until
        `incoming_bytes` fits under the byte + count budgets. Returns the
        evicted ids, or None when the incoming split can NEVER fit (or
        only by evicting something fresher than it — the reference's
        NoRoomAvailable).

        With `reserve_for`, the incoming bytes are accounted against
        `on_disk_bytes` IMMEDIATELY (tagged reserved on that split's
        entry), so a concurrent download admitted between this call and
        `register_on_disk` cannot overshoot the budget; the reservation
        is released by register_on_disk / forget / abort_download."""
        if incoming_bytes > self.max_bytes:
            return None
        evicted: list[str] = []
        on_disk = sorted(
            ((i["touch"], sid) for sid, i in self._splits.items()
             if i["status"] == ON_DISK))
        bytes_after = self.on_disk_bytes
        # reserved in-flight downloads hold a count slot too — otherwise
        # concurrent admissions protect the byte budget but overshoot
        # max_splits
        count_after = len(on_disk) + sum(
            1 for i in self._splits.values() if i.get("reserved"))
        idx = 0
        while (bytes_after + incoming_bytes > self.max_bytes
               or count_after + incoming_count > self.max_splits):
            if idx >= len(on_disk):
                return None
            _, victim = on_disk[idx]
            idx += 1
            bytes_after -= self._splits[victim]["num_bytes"]
            count_after -= 1
            evicted.append(victim)
        for victim in evicted:
            self.forget(victim)
        if reserve_for is not None:
            info = self._splits.get(reserve_for)
            if info is not None and info["status"] == DOWNLOADING:
                info["num_bytes"] = incoming_bytes
                info["reserved"] = True
                self.on_disk_bytes += incoming_bytes
        return evicted


class DiskSplitCache:
    """The on-disk cache + its download worker."""

    def __init__(self, root_path: str, storage_resolver,
                 max_bytes: int = 10 << 30, max_splits: int = 10_000):
        self.root_path = root_path
        self.storage_resolver = storage_resolver
        os.makedirs(root_path, exist_ok=True)
        # qwlint: disable-next-line=QW008 - on-disk cache downloader does real
        # file IO and timed event waits on real time; outside the DST-raced in-
        # memory path
        self._lock = threading.Lock()
        self.table = SplitTable(max_bytes, max_splits)
        # qwlint: disable-next-line=QW008 - on-disk cache downloader does real
        # file IO and timed event waits on real time; outside the DST-raced in-
        # memory path
        self._wakeup = threading.Event()
        # qwlint: disable-next-line=QW008 - on-disk cache downloader does real
        # file IO and timed event waits on real time; outside the DST-raced in-
        # memory path
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        # startup scan: drop interrupted downloads, adopt finished splits
        adopted: list[tuple[int, str]] = []
        for name in os.listdir(root_path):
            path = os.path.join(root_path, name)
            if not os.path.isfile(path):
                continue
            if name.endswith(".temp"):
                try:
                    os.remove(path)
                except OSError:
                    logger.warning("failed to remove temp file %s", path)
            elif name.endswith(".split"):
                adopted.append((os.path.getsize(path),
                                name[: -len(".split")]))
            else:
                logger.warning("unknown file in split cache dir: %s", path)
        # no recency survives a restart: adopt largest-first so a budget
        # shrink below evicts the biggest splits and keeps the most splits
        for num_bytes, split_id in sorted(adopted, reverse=True):
            self.table.register_on_disk(split_id, num_bytes)
        # a budget shrink across restarts evicts down to the new limit
        with self._lock:
            evicted = self.table.make_room(0, incoming_count=0) or []
        self._delete_files(evicted)

    # -- read path ----------------------------------------------------------
    def local_path(self, split_id: str) -> Optional[str]:
        """Local file path when the split is cached (counts a hit and
        freshens its eviction rank); None otherwise (counts a miss —
        candidate registration happens in the caller via report_split)."""
        with self._lock:
            info = self.table.info(split_id)
            if info is not None and info["status"] == ON_DISK:
                self.table.touch(split_id)
                _HITS.inc()
                return os.path.join(self.root_path, f"{split_id}.split")
        _MISSES.inc()
        return None

    def report_split(self, split_id: str, storage_uri: str,
                     num_bytes_hint: int = 0) -> None:
        """Reference `ReportSplit`: a leaf request touched this split —
        candidate it for download."""
        with self._lock:
            self.table.touch(split_id, storage_uri, num_bytes_hint)
        self._wakeup.set()

    # -- download side ------------------------------------------------------
    def download_one(self) -> Optional[str]:
        """Download the hottest candidate; returns its id or None when
        there is nothing to do / no room. Called by the worker loop and
        directly by tests."""
        with self._lock:
            candidate = self.table.best_candidate()
            if candidate is None:
                return None
            split_id, storage_uri = candidate
            self.table.start_download(split_id)
        try:
            storage = self.storage_resolver.resolve(storage_uri)
            payload = storage.get_all(f"{split_id}.split")
        # qwlint: disable-next-line=QW004 - background prefetch worker off
        # the query path: a failed download only drops the candidate, and
        # the worker loop must survive storage faults (incl. injected ones)
        except Exception as exc:  # noqa: BLE001 - worker must survive
            logger.warning("split cache download %s failed: %s",
                           split_id, exc)
            with self._lock:
                # a failing candidate is dropped, not retried forever
                self.table.forget(split_id)
            return None
        with self._lock:
            evicted = self.table.make_room(len(payload),
                                           reserve_for=split_id)
            if evicted is None:
                # cannot fit without evicting fresher data: drop candidacy
                self.table.forget(split_id)
                return None
        self._delete_files(evicted)
        if evicted:
            _EVICTIONS.inc(len(evicted))
        # Temp-write + rename must COMPLETE before the table claims the
        # split is on disk: a concurrent local_path() must never hand out
        # a path to a file that does not exist yet, and a failed write
        # (disk full) must not leave a permanent phantom entry.
        temp = os.path.join(self.root_path, f"{split_id}.split.temp")
        final = os.path.join(self.root_path, f"{split_id}.split")
        try:
            with open(temp, "wb") as fh:
                fh.write(payload)
            os.replace(temp, final)
        except OSError as exc:
            logger.warning("split cache write %s failed: %s", split_id, exc)
            with self._lock:
                self.table.forget(split_id)
            try:
                os.remove(temp)
            except OSError:
                pass
            return None
        with self._lock:
            self.table.register_on_disk(split_id, len(payload), storage_uri)
        _DOWNLOADS.inc()
        return split_id

    def _delete_files(self, split_ids: list[str]) -> None:
        for split_id in split_ids:
            try:
                os.remove(os.path.join(self.root_path, f"{split_id}.split"))
            except OSError:
                pass

    # -- worker -------------------------------------------------------------
    def start(self) -> None:
        if self._worker is None:
            # qwlint: disable-next-line=QW003 - long-lived background
            # downloader; deliberately NOT bound to the starting request's
            # deadline/tenant context
            # qwlint: disable-next-line=QW008 - on-disk cache downloader does
            # real file IO and timed event waits on real time; outside the DST-
            # raced in-memory path
            self._worker = threading.Thread(
                target=self._worker_loop, name="split-cache-dl", daemon=True)
            self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        self._wakeup.set()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            self._wakeup.wait(timeout=5.0)
            self._wakeup.clear()
            if self._stop.is_set():
                return
            while self.download_one() is not None:
                if self._stop.is_set():
                    return
