"""Storage abstraction.

Role of the reference's `quickwit-storage/src/storage.rs:50-143` `Storage`
trait: byte-addressed object storage under a base URI with put / get_slice /
get_all / delete / bulk_delete / file_num_bytes / exists, resolved from a URI
by a `StorageResolver`. Splits, metastore files and WAL snapshots all live
behind this seam, which is what keeps searchers stateless.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from ..common.uri import Protocol, Uri


class StorageError(IOError):
    def __init__(self, message: str, kind: str = "internal"):
        super().__init__(message)
        self.kind = kind  # "not_found" | "unauthorized" | "internal" | "timeout" | "deadline"


class Storage:
    """Abstract object storage rooted at `self.uri`."""

    def __init__(self, uri: Uri):
        self.uri = uri

    # --- writes ---------------------------------------------------------
    def put(self, path: str, payload: bytes) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def bulk_delete(self, paths: Iterable[str]) -> None:
        errors = []
        for path in paths:
            try:
                self.delete(path)
            except StorageError as exc:  # pragma: no cover - defensive
                if exc.kind != "not_found":
                    errors.append((path, exc))
        if errors:
            raise StorageError(f"bulk delete failed for {[p for p, _ in errors]}")

    # --- reads ----------------------------------------------------------
    def get_slice(self, path: str, start: int, end: int) -> bytes:
        """Bytes [start, end) of the object."""
        raise NotImplementedError

    def get_all(self, path: str) -> bytes:
        raise NotImplementedError

    def file_num_bytes(self, path: str) -> int:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        try:
            self.file_num_bytes(path)
            return True
        except StorageError:
            return False

    def list_files(self) -> list[str]:
        """Non-recursive object listing (used by file-backed metastore + GC)."""
        raise NotImplementedError

    def copy_to_file(self, path: str, dest_path: str) -> int:
        data = self.get_all(path)
        with open(dest_path, "wb") as f:
            f.write(data)
        return len(data)


class StorageResolver:
    """URI → Storage factory with per-backend constructors and an instance
    cache (reference: `storage_resolver.rs`)."""

    def __init__(self) -> None:
        self._factories: dict[Protocol, Callable[[Uri], Storage]] = {}
        self._cache: dict[str, Storage] = {}
        # qwlint: disable-next-line=QW008 - storage base/fakes leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self._lock = threading.Lock()

    def register(self, protocol: Protocol, factory: Callable[[Uri], Storage]) -> None:
        self._factories[protocol] = factory

    def resolve(self, uri: "Uri | str") -> Storage:
        if isinstance(uri, str):
            uri = Uri.parse(uri)
        key = str(uri)
        with self._lock:
            if key in self._cache:
                return self._cache[key]
            factory = self._factories.get(uri.protocol)
            if factory is None:
                raise StorageError(f"no storage backend for protocol {uri.protocol}")
            storage = factory(uri)
            self._cache[key] = storage
            return storage

    @staticmethod
    def for_test() -> "StorageResolver":
        from .local import LocalFileStorage
        from .ram import RamStorage
        from .s3 import S3CompatibleStorage
        resolver = StorageResolver()
        resolver.register(Protocol.FILE, LocalFileStorage)
        _ram_root = RamStorage(Uri.parse("ram:///"))
        resolver.register(Protocol.RAM, lambda uri: _ram_root.subdir(uri))
        # env-configured (QW_S3_ENDPOINT / AWS_*); hedged ranged reads by
        # default — S3's tail latency is the reason the wrapper exists
        resolver.register(Protocol.S3, _make_s3_storage)
        resolver.register(Protocol.AZURE, _make_azure_storage)
        resolver.register(Protocol.GCS, _make_gcs_storage)
        return resolver

    @staticmethod
    def default() -> "StorageResolver":
        return StorageResolver.for_test()


def _make_s3_storage(uri: Uri) -> Storage:
    from .s3 import S3CompatibleStorage, S3Config
    from .wrappers import TimeoutAndRetryStorage
    return TimeoutAndRetryStorage(S3CompatibleStorage(uri, S3Config.from_env()))


def _make_azure_storage(uri: Uri) -> Storage:
    from .azure import AzureBlobStorage
    from .wrappers import TimeoutAndRetryStorage
    return TimeoutAndRetryStorage(AzureBlobStorage(uri))


def _make_gcs_storage(uri: Uri) -> Storage:
    from .gcs import GcsStorage
    from .wrappers import TimeoutAndRetryStorage
    return TimeoutAndRetryStorage(GcsStorage(uri))
