"""Local filesystem storage (reference: `local_file_storage.rs`).

Writes are atomic: tmp file + rename, matching the reference's behavior so a
crashed upload never leaves a half-written split visible.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterable

from ..common.uri import Uri
from .base import Storage, StorageError


class LocalFileStorage(Storage):
    def __init__(self, uri: Uri):
        super().__init__(uri)
        self.root = uri.file_path
        os.makedirs(self.root, exist_ok=True)

    def _full(self, path: str) -> str:
        root = os.path.normpath(self.root)
        full = os.path.normpath(os.path.join(root, path))
        if full != root and os.path.commonpath([root, full]) != root:
            raise StorageError(f"path escapes storage root: {path}")
        return full

    def put(self, path: str, payload: bytes) -> None:
        full = self._full(path)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(full), prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, full)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def delete(self, path: str) -> None:
        try:
            os.unlink(self._full(path))
        except FileNotFoundError:
            raise StorageError(f"not found: {path}", kind="not_found")

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        try:
            with open(self._full(path), "rb") as f:
                f.seek(start)
                return f.read(end - start)
        except FileNotFoundError:
            raise StorageError(f"not found: {path}", kind="not_found")

    def get_all(self, path: str) -> bytes:
        try:
            with open(self._full(path), "rb") as f:
                return f.read()
        except FileNotFoundError:
            raise StorageError(f"not found: {path}", kind="not_found")

    def file_num_bytes(self, path: str) -> int:
        try:
            return os.stat(self._full(path)).st_size
        except FileNotFoundError:
            raise StorageError(f"not found: {path}", kind="not_found")

    def list_files(self) -> list[str]:
        out = []
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in filenames:
                if name.startswith(".tmp-"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                out.append(rel.replace(os.sep, "/"))
        return sorted(out)
