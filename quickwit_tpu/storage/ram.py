"""In-memory storage for tests (reference: `ram_storage.rs`)."""

from __future__ import annotations

import threading

from ..common.uri import Uri
from .base import Storage, StorageError


class RamStorage(Storage):
    def __init__(self, uri: Uri):
        super().__init__(uri)
        self._files: dict[str, bytes] = {}
        # qwlint: disable-next-line=QW008 - storage base/fakes leaf locks; pure
        # in-memory ops inside, never a seam primitive
        self._lock = threading.Lock()

    def subdir(self, uri: Uri) -> "RamStorage":
        """Share the same backing map, prefixing paths — mirrors the reference
        where all ram:// URIs resolve into one shared RamStorage tree."""
        child = RamStorage.__new__(RamStorage)
        Storage.__init__(child, uri)
        child._files = self._files
        child._lock = self._lock
        child._prefix = uri.path.lstrip("/")
        return child

    _prefix = ""

    def _key(self, path: str) -> str:
        return f"{self._prefix}/{path}" if self._prefix else path

    def put(self, path: str, payload: bytes) -> None:
        with self._lock:
            self._files[self._key(path)] = bytes(payload)

    def delete(self, path: str) -> None:
        with self._lock:
            if self._files.pop(self._key(path), None) is None:
                raise StorageError(f"not found: {path}", kind="not_found")

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        return self._get(path)[start:end]

    def get_all(self, path: str) -> bytes:
        return self._get(path)

    def _get(self, path: str) -> bytes:
        with self._lock:
            data = self._files.get(self._key(path))
        if data is None:
            raise StorageError(f"not found: {path}", kind="not_found")
        return data

    def file_num_bytes(self, path: str) -> int:
        return len(self._get(path))

    def list_files(self) -> list[str]:
        with self._lock:
            if not self._prefix:
                return sorted(self._files)
            prefix = self._prefix + "/"
            return sorted(k[len(prefix):] for k in self._files if k.startswith(prefix))
