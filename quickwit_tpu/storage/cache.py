"""Storage caches.

Role of the reference's cache hierarchy (`quickwit-storage/src/cache/`):
- `MemorySizedCache`: LRU bounded by total byte size (footer / fast-field
  caches).
- `ByteRangeCache`: caches object byte ranges with range-merge lookups, the
  short-lived per-leaf-search cache that deduplicates warmup reads.
- `CachingStorage`: a Storage wrapper consulting a cache before the backend
  (role of `CachingDirectory` one level up).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Iterable, Optional

from ..common import sync
from .base import Storage


class MemorySizedCache:
    """Byte-size-bounded LRU: key -> bytes.

    `on_evict(nbytes)` fires (outside the lock) whenever capacity pressure
    drops entries — the hierarchical leaf caches route it into their
    `qw_*_cache_evicted_bytes_total` counters. `resize` re-bounds a live
    cache (tenant-quota rebalancing, search/tenant_cache.py), evicting
    LRU-first down to the new capacity."""

    def __init__(self, capacity_bytes: int, on_evict=None):
        self.capacity_bytes = capacity_bytes
        self._entries: OrderedDict[str, bytes] = OrderedDict()
        self._size = 0
        self._lock = sync.lock("MemorySizedCache._lock")
        self.hits = 0
        self.misses = 0
        self.evicted_bytes = 0
        self._on_evict = on_evict

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            sync.note_write(self, "entries")
            data = self._entries.get(key)
            if data is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return data

    def _evict_to_capacity_locked(self) -> int:
        dropped = 0
        while self._size > self.capacity_bytes and self._entries:
            _, evicted = self._entries.popitem(last=False)
            self._size -= len(evicted)
            dropped += len(evicted)
        if dropped:
            self.evicted_bytes += dropped
        return dropped

    def _notify_evicted(self, dropped: int) -> None:
        if dropped and self._on_evict is not None:
            self._on_evict(dropped)

    def put(self, key: str, data: bytes) -> None:
        if len(data) > self.capacity_bytes:
            return  # reference behavior: items larger than the cache are not cached
        with self._lock:
            sync.note_write(self, "entries")
            old = self._entries.pop(key, None)
            if old is not None:
                self._size -= len(old)
            self._entries[key] = data
            self._size += len(data)
            dropped = self._evict_to_capacity_locked()
        self._notify_evicted(dropped)

    def delete(self, key: str) -> None:
        """Drop one entry (not counted as capacity eviction — used by the
        corruption chaos path, where the caller already accounts the miss)."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._size -= len(old)

    def resize(self, capacity_bytes: int) -> None:
        with self._lock:
            sync.note_write(self, "entries")
            self.capacity_bytes = capacity_bytes
            dropped = self._evict_to_capacity_locked()
        self._notify_evicted(dropped)

    def clear(self) -> int:
        """Forced full eviction (cache.evict chaos point); returns and
        counts the dropped bytes."""
        with self._lock:
            dropped = self._size
            self._entries.clear()
            self._size = 0
            if dropped:
                self.evicted_bytes += dropped
        self._notify_evicted(dropped)
        return dropped

    @property
    def size_bytes(self) -> int:
        # under the lock: `_size` is written by concurrent put/evict and a
        # torn read would leak into quota math (found by qwrace)
        with self._lock:
            sync.note_read(self, "entries")
            return self._size

    def stats_snapshot(self) -> dict:
        """Counters + size read atomically under the cache lock — the
        aggregation path must not race the hit/miss increments."""
        with self._lock:
            sync.note_read(self, "entries")
            return {"hits": self.hits, "misses": self.misses,
                    "size_bytes": self._size,
                    "evicted_bytes": self.evicted_bytes,
                    "capacity_bytes": self.capacity_bytes}


class ByteRangeCache:
    """Caches (path, [start,end)) ranges; a get is served if any cached range
    fully covers it. Ranges are stored per path sorted by start, adjacent/
    overlapping inserts are merged (reference: `byte_range_cache.rs`)."""

    def __init__(self) -> None:
        self._ranges: dict[str, list[tuple[int, int, bytes]]] = {}
        self._lock = sync.lock("ByteRangeCache._lock")
        self.hits = 0
        self.misses = 0

    def get(self, path: str, start: int, end: int) -> Optional[bytes]:
        with self._lock:
            sync.note_write(self, "ranges")
            for r_start, r_end, data in self._ranges.get(path, ()):
                if r_start <= start and end <= r_end:
                    self.hits += 1
                    return data[start - r_start:end - r_start]
            self.misses += 1
            return None

    def put(self, path: str, start: int, data: bytes) -> None:
        end = start + len(data)
        with self._lock:
            sync.note_write(self, "ranges")
            ranges = self._ranges.setdefault(path, [])
            merged_start, merged_end, merged = start, end, data
            keep: list[tuple[int, int, bytes]] = []
            for r_start, r_end, r_data in ranges:
                if r_end < merged_start or r_start > merged_end:
                    keep.append((r_start, r_end, r_data))
                    continue
                # overlap/adjacency: merge
                if r_start < merged_start:
                    merged = r_data[: merged_start - r_start] + merged
                    merged_start = r_start
                if r_end > merged_end:
                    merged = merged + r_data[len(r_data) - (r_end - merged_end):]
                    merged_end = r_end
            keep.append((merged_start, merged_end, merged))
            keep.sort(key=lambda r: r[0])
            self._ranges[path] = keep

    def invalidate(self, path: str) -> None:
        with self._lock:
            self._ranges.pop(path, None)

    @property
    def size_bytes(self) -> int:
        with self._lock:
            return sum(len(d) for ranges in self._ranges.values() for _, _, d in ranges)


class CachingStorage(Storage):
    """Read-through Storage wrapper over a ByteRangeCache."""

    def __init__(self, inner: Storage, cache: Optional[ByteRangeCache] = None):
        super().__init__(inner.uri)
        self.inner = inner
        self.cache = cache or ByteRangeCache()

    def put(self, path: str, payload: bytes) -> None:
        self.inner.put(path, payload)
        self.cache.invalidate(path)

    def delete(self, path: str) -> None:
        self.inner.delete(path)
        self.cache.invalidate(path)

    def bulk_delete(self, paths: Iterable[str]) -> None:
        self.inner.bulk_delete(paths)

    def get_slice(self, path: str, start: int, end: int) -> bytes:
        cached = self.cache.get(path, start, end)
        if cached is not None:
            return cached
        data = self.inner.get_slice(path, start, end)
        self.cache.put(path, start, data)
        return data

    def get_all(self, path: str) -> bytes:
        data = self.inner.get_all(path)
        self.cache.put(path, 0, data)
        return data

    def file_num_bytes(self, path: str) -> int:
        return self.inner.file_num_bytes(path)

    def list_files(self) -> list[str]:
        return self.inner.list_files()
