"""Public client for a quickwit_tpu cluster.

Role of the reference's `quickwit-rest-client` (`src/rest_client.rs:1`):
a typed client over the REST API for applications and tooling (the CLI
and integration tests use the same surface). Stdlib-only, persistent
connection, explicit errors, optional TLS with CA pinning.

    from quickwit_tpu.client import QuickwitClient

    qw = QuickwitClient("127.0.0.1:7280")
    qw.create_index({"index_id": "logs", "doc_mapping": {...}})
    qw.ingest("logs", [{"ts": 1, "body": "hello"}], commit="force")
    result = qw.search("logs", query="body:hello", max_hits=10)
    es = qw.es_search("logs", {"query": {"match": {"body": "hello"}}})
"""

from __future__ import annotations

import json
import ssl as ssl_mod
from http.client import HTTPConnection, HTTPSConnection
from typing import Any, Iterable, Optional
from urllib.parse import quote, urlencode


class QuickwitError(RuntimeError):
    """Non-2xx response; carries the HTTP status and decoded body."""

    def __init__(self, status: int, body: Any):
        if isinstance(body, dict):
            message = body.get("message") or body.get("error") or body
        else:
            message = body
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.body = body


class QuickwitClient:
    def __init__(self, endpoint: str, timeout_secs: float = 30.0,
                 tls: bool = False, ca_path: Optional[str] = None,
                 skip_verify: bool = False):
        host, _, port = endpoint.rpartition(":")
        self.host = host or endpoint
        self.port = int(port) if port else (443 if tls else 7280)
        self.timeout_secs = timeout_secs
        self._context: Optional[ssl_mod.SSLContext] = None
        if tls:
            if skip_verify:
                self._context = ssl_mod.SSLContext(
                    ssl_mod.PROTOCOL_TLS_CLIENT)
                self._context.check_hostname = False
                self._context.verify_mode = ssl_mod.CERT_NONE
            else:
                self._context = ssl_mod.create_default_context(
                    cafile=ca_path)
        self._conn: Optional[HTTPConnection] = None

    # --- transport --------------------------------------------------------
    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            finally:
                self._conn = None

    def _connection(self) -> HTTPConnection:
        if self._conn is None:
            if self._context is not None:
                self._conn = HTTPSConnection(
                    self.host, self.port, timeout=self.timeout_secs,
                    context=self._context)
            else:
                self._conn = HTTPConnection(self.host, self.port,
                                            timeout=self.timeout_secs)
        return self._conn

    def request(self, method: str, path: str, body: Any = None,
                raw: Optional[bytes] = None,
                content_type: str = "application/json") -> Any:
        payload = raw if raw is not None else (
            json.dumps(body).encode() if body is not None else None)
        idempotent = method in ("GET", "HEAD", "DELETE")
        for attempt in (1, 2):  # one re-dial on a dead kept-alive conn
            conn = self._connection()
            sent = False
            try:
                conn.request(method, path, body=payload,
                             headers={"Content-Type": content_type})
                sent = True
                response = conn.getresponse()
                data = response.read()
                break
            except (OSError, ConnectionError):
                self.close()
                # once a non-idempotent request was TRANSMITTED, a retry
                # could duplicate its effect (e.g. re-ingest a batch the
                # server committed before the connection dropped)
                if attempt == 2 or (sent and not idempotent):
                    raise
        decoded = json.loads(data) if data else None
        if response.status >= 300:
            raise QuickwitError(response.status, decoded)
        return decoded

    # --- index management --------------------------------------------------
    def create_index(self, index_config: dict) -> dict:
        return self.request("POST", "/api/v1/indexes", index_config)

    def update_index(self, index_id: str, update: dict) -> dict:
        """Live config update: search_settings, retention,
        indexing_settings, append-only doc_mapping additions."""
        return self.request(
            "PUT", f"/api/v1/indexes/{quote(index_id)}", update)

    def delete_index(self, index_id: str) -> dict:
        return self.request("DELETE", f"/api/v1/indexes/{quote(index_id)}")

    def list_indexes(self) -> list:
        return self.request("GET", "/api/v1/indexes")

    def list_splits(self, index_id: str) -> list:
        return self.request(
            "GET", f"/api/v1/indexes/{quote(index_id)}/splits")["splits"]

    # --- sources -----------------------------------------------------------
    def create_source(self, index_id: str, source_config: dict) -> dict:
        return self.request(
            "POST", f"/api/v1/indexes/{quote(index_id)}/sources",
            source_config)

    def delete_source(self, index_id: str, source_id: str) -> dict:
        return self.request(
            "DELETE", f"/api/v1/indexes/{quote(index_id)}/sources/"
                      f"{quote(source_id)}")

    # --- ingest ------------------------------------------------------------
    def ingest(self, index_id: str, docs: Iterable[dict],
               commit: str = "auto") -> dict:
        ndjson = "\n".join(json.dumps(d) for d in docs).encode()
        return self.request(
            "POST", f"/api/v1/{quote(index_id)}/ingest?commit={commit}",
            raw=ndjson, content_type="application/x-ndjson")

    # --- search ------------------------------------------------------------
    def search(self, index_id: str, query: str = "*", max_hits: int = 20,
               start_offset: int = 0, sort_by: Optional[str] = None,
               start_timestamp: Optional[int] = None,
               end_timestamp: Optional[int] = None,
               aggs: Optional[dict] = None) -> dict:
        """The native search API (query-string syntax)."""
        body: dict[str, Any] = {"query": query, "max_hits": max_hits,
                                "start_offset": start_offset}
        if sort_by is not None:
            body["sort_by"] = sort_by
        if start_timestamp is not None:
            body["start_timestamp"] = start_timestamp
        if end_timestamp is not None:
            body["end_timestamp"] = end_timestamp
        if aggs:
            body["aggs"] = aggs
        return self.request(
            "POST", f"/api/v1/{quote(index_id)}/search", body)

    def es_search(self, index_id: str, body: dict) -> dict:
        """The Elasticsearch-compatible `_search` API."""
        return self.request(
            "POST", f"/api/v1/_elastic/{quote(index_id)}/_search", body)

    def scroll(self, index_id: str, query: str = "*", max_hits: int = 20,
               scroll: str = "1m"):
        """Iterate every page of a scrolled search."""
        params = urlencode({"query": query, "max_hits": max_hits,
                            "scroll": scroll})
        page = self.request(
            "GET", f"/api/v1/{quote(index_id)}/search?{params}")
        while True:
            yield page
            scroll_id = page.get("scroll_id")
            if not scroll_id or not page.get("hits"):
                return
            page = self.request(
                "GET", f"/api/v1/scroll?scroll_id={quote(scroll_id)}")
            if not page.get("hits"):
                return

    def sql(self, query: str) -> dict:
        return self.request("POST", "/api/v1/_sql", {"query": query})

    def create_delete_task(self, index_id: str, es_query: dict) -> dict:
        return self.request(
            "POST", f"/api/v1/{quote(index_id)}/delete-tasks",
            {"query": es_query})

    # --- cluster / ops ------------------------------------------------------
    def cluster(self) -> dict:
        return self.request("GET", "/api/v1/cluster")

    def health(self) -> bool:
        try:
            self.request("GET", "/health/livez")
            return True
        except (QuickwitError, OSError):
            return False
