// Native inverted-index builder — the indexing hot loop.
//
// Role of tantivy's segment writer driven by the reference's Indexer actor
// (`indexer.rs:362`: tokenize -> term hash -> postings accumulation), which
// is native Rust in the reference. Python feeds a concatenated UTF-8 buffer
// of field values with (value -> doc) mapping; this builds:
//   - the sorted term dictionary (blob + offsets + df)
//   - postings arenas (doc ids + term freqs, padded to POSTING_PAD with the
//     out-of-bounds sentinel, matching index/format.py's layout)
//   - optional per-(posting) position lists (record="position" fields)
//   - per-doc fieldnorms (token counts)
//
// Tokenizer parity: byte-for-byte identical to query/tokenizers.py
// `default` — word chars are [0-9A-Za-z], U+00C0..U+024F, U+0400..U+04FF;
// tokens lowercase (ASCII +0x20; Latin-1 supplement/Extended-A/B and
// Cyrillic per Unicode simple case folding); tokens longer than 255 chars
// are dropped. CPython C API only (no pybind11 in this image).

#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kPostingPad = 128;
constexpr int kMaxTokenLen = 255;  // in codepoints

inline bool is_word_cp(uint32_t cp) {
  if ((cp >= '0' && cp <= '9') || (cp >= 'A' && cp <= 'Z') ||
      (cp >= 'a' && cp <= 'z'))
    return true;
  if (cp >= 0x00C0 && cp <= 0x024F) return true;  // latin supplement/ext A+B
  if (cp >= 0x0400 && cp <= 0x04FF) return true;  // cyrillic
  return false;
}

// Unicode simple lowercase for the ranges is_word_cp admits.
inline uint32_t lower_cp(uint32_t cp) {
  if (cp >= 'A' && cp <= 'Z') return cp + 0x20;
  if (cp >= 0x00C0 && cp <= 0x00DE && cp != 0x00D7) return cp + 0x20;
  if (cp >= 0x0100 && cp <= 0x0137) return cp | 1;            // pairs
  if (cp >= 0x0139 && cp <= 0x0148) return ((cp - 1) | 1) + 1;  // odd pairs
  if (cp >= 0x014A && cp <= 0x0177) return cp | 1;
  if (cp == 0x0178) return 0x00FF;
  if (cp >= 0x0179 && cp <= 0x017E) return ((cp - 1) | 1) + 1;
  if (cp >= 0x0182 && cp <= 0x0185) return cp | 1;
  if (cp >= 0x01A0 && cp <= 0x01A5) return cp | 1;
  if (cp >= 0x01B3 && cp <= 0x01B6) return ((cp - 1) | 1) + 1;
  if (cp >= 0x01CD && cp <= 0x01DC) return ((cp - 1) | 1) + 1;
  if (cp >= 0x01DE && cp <= 0x01EF) return cp | 1;
  if (cp >= 0x01F4 && cp <= 0x01F5) return 0x01F5;
  if (cp >= 0x01F8 && cp <= 0x021F) return cp | 1;
  if (cp >= 0x0222 && cp <= 0x0233) return cp | 1;
  if (cp >= 0x0410 && cp <= 0x042F) return cp + 0x20;  // А-Я
  if (cp >= 0x0400 && cp <= 0x040F) return cp + 0x50;  // Ѐ-Џ
  if (cp >= 0x0460 && cp <= 0x0481) return cp | 1;
  if (cp >= 0x048A && cp <= 0x04BF) return cp | 1;
  if (cp >= 0x04C1 && cp <= 0x04CE) return ((cp - 1) | 1) + 1;
  if (cp >= 0x04D0 && cp <= 0x04FF) return cp | 1;
  return cp;
}

inline void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out.push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// Decode the next UTF-8 codepoint; on malformed input consume 1 byte and
// return 0xFFFD (matches Python's handling of already-valid str: malformed
// input cannot occur from CPython-encoded buffers).
inline uint32_t next_cp(const uint8_t* buf, size_t len, size_t& i) {
  uint8_t b0 = buf[i];
  if (b0 < 0x80) { i += 1; return b0; }
  if ((b0 >> 5) == 0x6 && i + 1 < len) {
    uint32_t cp = ((b0 & 0x1F) << 6) | (buf[i + 1] & 0x3F);
    i += 2; return cp;
  }
  if ((b0 >> 4) == 0xE && i + 2 < len) {
    uint32_t cp = ((b0 & 0x0F) << 12) | ((buf[i + 1] & 0x3F) << 6) |
                  (buf[i + 2] & 0x3F);
    i += 3; return cp;
  }
  if ((b0 >> 3) == 0x1E && i + 3 < len) {
    uint32_t cp = ((b0 & 0x07) << 18) | ((buf[i + 1] & 0x3F) << 12) |
                  ((buf[i + 2] & 0x3F) << 6) | (buf[i + 3] & 0x3F);
    i += 4; return cp;
  }
  i += 1;
  return 0xFFFD;
}

struct Posting {
  int32_t doc;
  int32_t tf;
  std::vector<int32_t> positions;
};

struct TermEntry {
  std::vector<Posting> postings;
};

struct Builder {
  std::unordered_map<std::string, TermEntry> terms;
  std::vector<int32_t> fieldnorms;   // token count per doc
  std::vector<int32_t> pos_base;     // next position base per doc (with gaps)
  int64_t total_tokens = 0;
  bool with_positions = false;
};

void add_value(Builder& b, int32_t doc, const uint8_t* buf, size_t len) {
  if (static_cast<size_t>(doc) >= b.fieldnorms.size()) {
    b.fieldnorms.resize(doc + 1, 0);
    b.pos_base.resize(doc + 1, 0);
  }
  int32_t base = b.pos_base[doc];
  // position indexes every token (even dropped overlong ones occupy a
  // position slot — tokenizer parity with query/tokenizers.py enumerate());
  // kept counts only indexed tokens (fieldnorm / BM25 doc length).
  int32_t position = 0;
  int32_t kept = 0;
  std::string token;
  size_t token_cps = 0;
  size_t i = 0;
  auto flush = [&](void) {
    if (!token.empty()) {
      if (token_cps <= kMaxTokenLen) {
        TermEntry& entry = b.terms[token];
        if (!entry.postings.empty() && entry.postings.back().doc == doc) {
          entry.postings.back().tf += 1;
          if (b.with_positions)
            entry.postings.back().positions.push_back(base + position);
        } else {
          Posting p;
          p.doc = doc;
          p.tf = 1;
          if (b.with_positions) p.positions.push_back(base + position);
          entry.postings.push_back(std::move(p));
        }
        kept += 1;
      }
      position += 1;
      token.clear();
      token_cps = 0;
    }
  };
  while (i < len) {
    uint32_t cp = next_cp(buf, len, i);
    if (is_word_cp(cp)) {
      append_utf8(token, lower_cp(cp));
      token_cps += 1;
    } else {
      flush();
    }
  }
  flush();
  b.fieldnorms[doc] += kept;
  // +1 gap between values so phrases never match across value boundaries
  b.pos_base[doc] = base + kept + 1;
  b.total_tokens += kept;
}

inline int64_t pad_to(int64_t n, int64_t m) { return ((n + m - 1) / m) * m; }

// ---------------------------------------------------------------------------
// Python bindings

struct BuilderCapsule {
  Builder builder;
};

void capsule_destructor(PyObject* capsule) {
  delete static_cast<BuilderCapsule*>(
      PyCapsule_GetPointer(capsule, "fastindex.Builder"));
}

PyObject* py_new_builder(PyObject*, PyObject* args) {
  int with_positions = 0;
  if (!PyArg_ParseTuple(args, "p", &with_positions)) return nullptr;
  auto* cap = new BuilderCapsule();
  cap->builder.with_positions = with_positions != 0;
  return PyCapsule_New(cap, "fastindex.Builder", capsule_destructor);
}

Builder* get_builder(PyObject* capsule) {
  auto* cap = static_cast<BuilderCapsule*>(
      PyCapsule_GetPointer(capsule, "fastindex.Builder"));
  return cap ? &cap->builder : nullptr;
}

// add_values(builder, doc_ids_bytes(int32 LE), text_blob, offsets_bytes(int64 LE))
PyObject* py_add_values(PyObject*, PyObject* args) {
  PyObject* capsule;
  Py_buffer doc_ids_buf, text_buf, offsets_buf;
  if (!PyArg_ParseTuple(args, "Oy*y*y*", &capsule, &doc_ids_buf, &text_buf,
                        &offsets_buf))
    return nullptr;
  Builder* b = get_builder(capsule);
  if (b == nullptr) {
    PyBuffer_Release(&doc_ids_buf);
    PyBuffer_Release(&text_buf);
    PyBuffer_Release(&offsets_buf);
    PyErr_SetString(PyExc_ValueError, "invalid builder capsule");
    return nullptr;
  }
  const auto* doc_ids = static_cast<const int32_t*>(doc_ids_buf.buf);
  const auto* text = static_cast<const uint8_t*>(text_buf.buf);
  const auto* offsets = static_cast<const int64_t*>(offsets_buf.buf);
  Py_ssize_t n_values = doc_ids_buf.len / static_cast<Py_ssize_t>(sizeof(int32_t));
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t v = 0; v < n_values; ++v) {
    add_value(*b, doc_ids[v], text + offsets[v],
              static_cast<size_t>(offsets[v + 1] - offsets[v]));
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&doc_ids_buf);
  PyBuffer_Release(&text_buf);
  PyBuffer_Release(&offsets_buf);
  Py_RETURN_NONE;
}

// finish(builder, num_docs_padded) ->
//   (terms_blob, term_offsets, dfs, post_offs, post_lens,
//    ids_arena, tfs_arena, fieldnorms, total_tokens,
//    pos_offsets|None, pos_data|None)      -- all bytes objects (LE arrays)
PyObject* py_finish(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long num_docs_padded;
  if (!PyArg_ParseTuple(args, "OL", &capsule, &num_docs_padded)) return nullptr;
  Builder* b = get_builder(capsule);
  if (b == nullptr) {
    PyErr_SetString(PyExc_ValueError, "invalid builder capsule");
    return nullptr;
  }

  std::vector<const std::string*> sorted_terms;
  sorted_terms.reserve(b->terms.size());
  for (const auto& kv : b->terms) sorted_terms.push_back(&kv.first);
  std::string blob;
  std::vector<int64_t> term_offsets;
  std::vector<int32_t> dfs;
  std::vector<int64_t> post_offs;
  std::vector<int32_t> post_lens;
  std::vector<int32_t> ids_arena;
  std::vector<int32_t> tfs_arena;
  std::vector<int64_t> pos_offsets;
  std::vector<int32_t> pos_data;

  Py_BEGIN_ALLOW_THREADS
  std::sort(sorted_terms.begin(), sorted_terms.end(),
            [](const std::string* a, const std::string* s) { return *a < *s; });
  size_t n_terms = sorted_terms.size();
  term_offsets.reserve(n_terms + 1);
  term_offsets.push_back(0);
  dfs.reserve(n_terms);
  post_offs.reserve(n_terms);
  post_lens.reserve(n_terms);
  int64_t total_padded = 0;
  for (const std::string* term : sorted_terms) {
    int64_t df = static_cast<int64_t>(b->terms[*term].postings.size());
    total_padded += pad_to(df, kPostingPad);
  }
  ids_arena.assign(total_padded, static_cast<int32_t>(num_docs_padded));
  tfs_arena.assign(total_padded, 0);
  if (b->with_positions) pos_offsets.assign(total_padded + 1, 0);
  int64_t cursor = 0;
  int64_t pos_cursor = 0;
  for (const std::string* term : sorted_terms) {
    blob += *term;
    term_offsets.push_back(static_cast<int64_t>(blob.size()));
    auto& postings = b->terms[*term].postings;
    int64_t df = static_cast<int64_t>(postings.size());
    int64_t padded = pad_to(df, kPostingPad);
    dfs.push_back(static_cast<int32_t>(df));
    post_offs.push_back(cursor);
    post_lens.push_back(static_cast<int32_t>(padded));
    for (int64_t i = 0; i < df; ++i) {
      ids_arena[cursor + i] = postings[i].doc;
      tfs_arena[cursor + i] = postings[i].tf;
      if (b->with_positions) {
        pos_offsets[cursor + i] = pos_cursor;
        for (int32_t p : postings[i].positions) pos_data.push_back(p);
        pos_cursor += static_cast<int64_t>(postings[i].positions.size());
      }
    }
    if (b->with_positions) {
      for (int64_t i = df; i <= padded && cursor + i <= total_padded; ++i)
        pos_offsets[cursor + i] = pos_cursor;
    }
    cursor += padded;
  }
  Py_END_ALLOW_THREADS

  std::vector<int32_t> norms(num_docs_padded, 0);
  size_t copy_n = std::min(b->fieldnorms.size(),
                           static_cast<size_t>(num_docs_padded));
  std::memcpy(norms.data(), b->fieldnorms.data(), copy_n * sizeof(int32_t));

  auto bytes_of = [](const void* data, size_t nbytes) {
    return PyBytes_FromStringAndSize(static_cast<const char*>(data),
                                     static_cast<Py_ssize_t>(nbytes));
  };
  PyObject* result = PyTuple_New(11);
  PyTuple_SET_ITEM(result, 0, bytes_of(blob.data(), blob.size()));
  PyTuple_SET_ITEM(result, 1, bytes_of(term_offsets.data(),
                                       term_offsets.size() * 8));
  PyTuple_SET_ITEM(result, 2, bytes_of(dfs.data(), dfs.size() * 4));
  PyTuple_SET_ITEM(result, 3, bytes_of(post_offs.data(), post_offs.size() * 8));
  PyTuple_SET_ITEM(result, 4, bytes_of(post_lens.data(), post_lens.size() * 4));
  PyTuple_SET_ITEM(result, 5, bytes_of(ids_arena.data(), ids_arena.size() * 4));
  PyTuple_SET_ITEM(result, 6, bytes_of(tfs_arena.data(), tfs_arena.size() * 4));
  PyTuple_SET_ITEM(result, 7, bytes_of(norms.data(), norms.size() * 4));
  PyTuple_SET_ITEM(result, 8, PyLong_FromLongLong(b->total_tokens));
  if (b->with_positions) {
    PyTuple_SET_ITEM(result, 9, bytes_of(pos_offsets.data(),
                                         pos_offsets.size() * 8));
    PyTuple_SET_ITEM(result, 10, bytes_of(pos_data.data(),
                                          pos_data.size() * 4));
  } else {
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(result, 9, Py_None);
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(result, 10, Py_None);
  }
  return result;
}

PyMethodDef kMethods[] = {
    {"new_builder", py_new_builder, METH_VARARGS,
     "new_builder(with_positions) -> capsule"},
    {"add_values", py_add_values, METH_VARARGS,
     "add_values(builder, doc_ids_i32, text_blob, offsets_i64)"},
    {"finish", py_finish, METH_VARARGS,
     "finish(builder, num_docs_padded) -> arrays tuple"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "fastindex",
                       "native inverted-index builder", -1, kMethods,
                       nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit_fastindex(void) { return PyModule_Create(&kModule); }
