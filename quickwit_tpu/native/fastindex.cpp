// Native inverted-index builder — the indexing hot loop.
//
// Role of tantivy's segment writer driven by the reference's Indexer actor
// (`indexer.rs:362`: tokenize -> term hash -> postings accumulation), which
// is native Rust in the reference. Python feeds a concatenated UTF-8 buffer
// of field values with (value -> doc) mapping; this builds:
//   - the sorted term dictionary (blob + offsets + df)
//   - postings arenas (doc ids + term freqs, padded to POSTING_PAD with the
//     out-of-bounds sentinel, matching index/format.py's layout)
//   - optional per-(posting) position lists (record="position" fields)
//   - per-doc fieldnorms (token counts)
//
// Tokenizer parity: byte-for-byte identical to query/tokenizers.py
// `default` — word chars are [0-9A-Za-z], U+00C0..U+024F, U+0400..U+04FF;
// tokens lowercase (ASCII +0x20; Latin-1 supplement/Extended-A/B and
// Cyrillic per Unicode simple case folding); tokens longer than 255 chars
// are dropped. CPython C API only (no pybind11 in this image).

#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kPostingPad = 128;
constexpr int kMaxTokenLen = 255;  // in codepoints

inline bool is_word_cp(uint32_t cp) {
  if ((cp >= '0' && cp <= '9') || (cp >= 'A' && cp <= 'Z') ||
      (cp >= 'a' && cp <= 'z'))
    return true;
  if (cp >= 0x00C0 && cp <= 0x024F) return true;  // latin supplement/ext A+B
  if (cp >= 0x0400 && cp <= 0x04FF) return true;  // cyrillic
  return false;
}

// Unicode simple lowercase for the ranges is_word_cp admits.
inline uint32_t lower_cp(uint32_t cp) {
  if (cp >= 'A' && cp <= 'Z') return cp + 0x20;
  if (cp >= 0x00C0 && cp <= 0x00DE && cp != 0x00D7) return cp + 0x20;
  if (cp >= 0x0100 && cp <= 0x0137) return cp | 1;            // pairs
  if (cp >= 0x0139 && cp <= 0x0148) return ((cp - 1) | 1) + 1;  // odd pairs
  if (cp >= 0x014A && cp <= 0x0177) return cp | 1;
  if (cp == 0x0178) return 0x00FF;
  if (cp >= 0x0179 && cp <= 0x017E) return ((cp - 1) | 1) + 1;
  if (cp >= 0x0182 && cp <= 0x0185) return cp | 1;
  if (cp >= 0x01A0 && cp <= 0x01A5) return cp | 1;
  if (cp >= 0x01B3 && cp <= 0x01B6) return ((cp - 1) | 1) + 1;
  if (cp >= 0x01CD && cp <= 0x01DC) return ((cp - 1) | 1) + 1;
  if (cp >= 0x01DE && cp <= 0x01EF) return cp | 1;
  if (cp >= 0x01F4 && cp <= 0x01F5) return 0x01F5;
  if (cp >= 0x01F8 && cp <= 0x021F) return cp | 1;
  if (cp >= 0x0222 && cp <= 0x0233) return cp | 1;
  if (cp >= 0x0410 && cp <= 0x042F) return cp + 0x20;  // А-Я
  if (cp >= 0x0400 && cp <= 0x040F) return cp + 0x50;  // Ѐ-Џ
  if (cp >= 0x0460 && cp <= 0x0481) return cp | 1;
  if (cp >= 0x048A && cp <= 0x04BF) return cp | 1;
  if (cp >= 0x04C1 && cp <= 0x04CE) return ((cp - 1) | 1) + 1;
  if (cp >= 0x04D0 && cp <= 0x04FF) return cp | 1;
  return cp;
}

inline void append_utf8(std::string& out, uint32_t cp) {
  if (cp < 0x80) {
    out.push_back(static_cast<char>(cp));
  } else if (cp < 0x800) {
    out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  } else {
    out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
    out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
  }
}

// Decode the next UTF-8 codepoint; on malformed input consume 1 byte and
// return 0xFFFD (matches Python's handling of already-valid str: malformed
// input cannot occur from CPython-encoded buffers).
inline uint32_t next_cp(const uint8_t* buf, size_t len, size_t& i) {
  uint8_t b0 = buf[i];
  if (b0 < 0x80) { i += 1; return b0; }
  if ((b0 >> 5) == 0x6 && i + 1 < len) {
    uint32_t cp = ((b0 & 0x1F) << 6) | (buf[i + 1] & 0x3F);
    i += 2; return cp;
  }
  if ((b0 >> 4) == 0xE && i + 2 < len) {
    uint32_t cp = ((b0 & 0x0F) << 12) | ((buf[i + 1] & 0x3F) << 6) |
                  (buf[i + 2] & 0x3F);
    i += 3; return cp;
  }
  if ((b0 >> 3) == 0x1E && i + 3 < len) {
    uint32_t cp = ((b0 & 0x07) << 18) | ((buf[i + 1] & 0x3F) << 12) |
                  ((buf[i + 2] & 0x3F) << 6) | (buf[i + 3] & 0x3F);
    i += 4; return cp;
  }
  i += 1;
  return 0xFFFD;
}

struct Posting {
  int32_t doc;
  int32_t tf;
  std::vector<int32_t> positions;
};

struct TermEntry {
  std::vector<Posting> postings;
};

struct Builder {
  std::unordered_map<std::string, TermEntry> terms;
  std::vector<int32_t> fieldnorms;   // token count per doc
  std::vector<int32_t> pos_base;     // next position base per doc (with gaps)
  int64_t total_tokens = 0;
  bool with_positions = false;
};

void add_value(Builder& b, int32_t doc, const uint8_t* buf, size_t len) {
  if (static_cast<size_t>(doc) >= b.fieldnorms.size()) {
    b.fieldnorms.resize(doc + 1, 0);
    b.pos_base.resize(doc + 1, 0);
  }
  int32_t base = b.pos_base[doc];
  // position indexes every token (even dropped overlong ones occupy a
  // position slot — tokenizer parity with query/tokenizers.py enumerate());
  // kept counts only indexed tokens (fieldnorm / BM25 doc length).
  int32_t position = 0;
  int32_t kept = 0;
  std::string token;
  size_t token_cps = 0;
  size_t i = 0;
  auto flush = [&](void) {
    if (!token.empty()) {
      if (token_cps <= kMaxTokenLen) {
        TermEntry& entry = b.terms[token];
        if (!entry.postings.empty() && entry.postings.back().doc == doc) {
          entry.postings.back().tf += 1;
          if (b.with_positions)
            entry.postings.back().positions.push_back(base + position);
        } else {
          Posting p;
          p.doc = doc;
          p.tf = 1;
          if (b.with_positions) p.positions.push_back(base + position);
          entry.postings.push_back(std::move(p));
        }
        kept += 1;
      }
      position += 1;
      token.clear();
      token_cps = 0;
    }
  };
  while (i < len) {
    uint32_t cp = next_cp(buf, len, i);
    if (is_word_cp(cp)) {
      append_utf8(token, lower_cp(cp));
      token_cps += 1;
    } else {
      flush();
    }
  }
  flush();
  b.fieldnorms[doc] += kept;
  // +1 gap between values so phrases never match across value boundaries
  b.pos_base[doc] = base + kept + 1;
  b.total_tokens += kept;
}

inline int64_t pad_to(int64_t n, int64_t m) { return ((n + m - 1) / m) * m; }

// ---------------------------------------------------------------------------
// Python bindings

struct BuilderCapsule {
  Builder builder;
};

void capsule_destructor(PyObject* capsule) {
  delete static_cast<BuilderCapsule*>(
      PyCapsule_GetPointer(capsule, "fastindex.Builder"));
}

PyObject* py_new_builder(PyObject*, PyObject* args) {
  int with_positions = 0;
  if (!PyArg_ParseTuple(args, "p", &with_positions)) return nullptr;
  auto* cap = new BuilderCapsule();
  cap->builder.with_positions = with_positions != 0;
  return PyCapsule_New(cap, "fastindex.Builder", capsule_destructor);
}

Builder* get_builder(PyObject* capsule) {
  auto* cap = static_cast<BuilderCapsule*>(
      PyCapsule_GetPointer(capsule, "fastindex.Builder"));
  return cap ? &cap->builder : nullptr;
}

// add_values(builder, doc_ids_bytes(int32 LE), text_blob, offsets_bytes(int64 LE))
PyObject* py_add_values(PyObject*, PyObject* args) {
  PyObject* capsule;
  Py_buffer doc_ids_buf, text_buf, offsets_buf;
  if (!PyArg_ParseTuple(args, "Oy*y*y*", &capsule, &doc_ids_buf, &text_buf,
                        &offsets_buf))
    return nullptr;
  Builder* b = get_builder(capsule);
  if (b == nullptr) {
    PyBuffer_Release(&doc_ids_buf);
    PyBuffer_Release(&text_buf);
    PyBuffer_Release(&offsets_buf);
    PyErr_SetString(PyExc_ValueError, "invalid builder capsule");
    return nullptr;
  }
  const auto* doc_ids = static_cast<const int32_t*>(doc_ids_buf.buf);
  const auto* text = static_cast<const uint8_t*>(text_buf.buf);
  const auto* offsets = static_cast<const int64_t*>(offsets_buf.buf);
  Py_ssize_t n_values = doc_ids_buf.len / static_cast<Py_ssize_t>(sizeof(int32_t));
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t v = 0; v < n_values; ++v) {
    add_value(*b, doc_ids[v], text + offsets[v],
              static_cast<size_t>(offsets[v + 1] - offsets[v]));
  }
  Py_END_ALLOW_THREADS
  PyBuffer_Release(&doc_ids_buf);
  PyBuffer_Release(&text_buf);
  PyBuffer_Release(&offsets_buf);
  Py_RETURN_NONE;
}

// finish(builder, num_docs_padded) ->
//   (terms_blob, term_offsets, dfs, post_offs, post_lens,
//    ids_arena, tfs_arena, fieldnorms, total_tokens,
//    pos_offsets|None, pos_data|None)      -- all bytes objects (LE arrays)
PyObject* py_finish(PyObject*, PyObject* args) {
  PyObject* capsule;
  long long num_docs_padded;
  if (!PyArg_ParseTuple(args, "OL", &capsule, &num_docs_padded)) return nullptr;
  Builder* b = get_builder(capsule);
  if (b == nullptr) {
    PyErr_SetString(PyExc_ValueError, "invalid builder capsule");
    return nullptr;
  }

  std::vector<const std::string*> sorted_terms;
  sorted_terms.reserve(b->terms.size());
  for (const auto& kv : b->terms) sorted_terms.push_back(&kv.first);
  std::string blob;
  std::vector<int64_t> term_offsets;
  std::vector<int32_t> dfs;
  std::vector<int64_t> post_offs;
  std::vector<int32_t> post_lens;
  std::vector<int32_t> ids_arena;
  std::vector<int32_t> tfs_arena;
  std::vector<int64_t> pos_offsets;
  std::vector<int32_t> pos_data;

  Py_BEGIN_ALLOW_THREADS
  std::sort(sorted_terms.begin(), sorted_terms.end(),
            [](const std::string* a, const std::string* s) { return *a < *s; });
  size_t n_terms = sorted_terms.size();
  term_offsets.reserve(n_terms + 1);
  term_offsets.push_back(0);
  dfs.reserve(n_terms);
  post_offs.reserve(n_terms);
  post_lens.reserve(n_terms);
  int64_t total_padded = 0;
  for (const std::string* term : sorted_terms) {
    int64_t df = static_cast<int64_t>(b->terms[*term].postings.size());
    total_padded += pad_to(df, kPostingPad);
  }
  ids_arena.assign(total_padded, static_cast<int32_t>(num_docs_padded));
  tfs_arena.assign(total_padded, 0);
  if (b->with_positions) pos_offsets.assign(total_padded + 1, 0);
  int64_t cursor = 0;
  int64_t pos_cursor = 0;
  for (const std::string* term : sorted_terms) {
    blob += *term;
    term_offsets.push_back(static_cast<int64_t>(blob.size()));
    auto& postings = b->terms[*term].postings;
    int64_t df = static_cast<int64_t>(postings.size());
    int64_t padded = pad_to(df, kPostingPad);
    dfs.push_back(static_cast<int32_t>(df));
    post_offs.push_back(cursor);
    post_lens.push_back(static_cast<int32_t>(padded));
    for (int64_t i = 0; i < df; ++i) {
      ids_arena[cursor + i] = postings[i].doc;
      tfs_arena[cursor + i] = postings[i].tf;
      if (b->with_positions) {
        pos_offsets[cursor + i] = pos_cursor;
        for (int32_t p : postings[i].positions) pos_data.push_back(p);
        pos_cursor += static_cast<int64_t>(postings[i].positions.size());
      }
    }
    if (b->with_positions) {
      for (int64_t i = df; i <= padded && cursor + i <= total_padded; ++i)
        pos_offsets[cursor + i] = pos_cursor;
    }
    cursor += padded;
  }
  Py_END_ALLOW_THREADS

  std::vector<int32_t> norms(num_docs_padded, 0);
  size_t copy_n = std::min(b->fieldnorms.size(),
                           static_cast<size_t>(num_docs_padded));
  std::memcpy(norms.data(), b->fieldnorms.data(), copy_n * sizeof(int32_t));

  auto bytes_of = [](const void* data, size_t nbytes) {
    return PyBytes_FromStringAndSize(static_cast<const char*>(data),
                                     static_cast<Py_ssize_t>(nbytes));
  };
  PyObject* result = PyTuple_New(11);
  PyTuple_SET_ITEM(result, 0, bytes_of(blob.data(), blob.size()));
  PyTuple_SET_ITEM(result, 1, bytes_of(term_offsets.data(),
                                       term_offsets.size() * 8));
  PyTuple_SET_ITEM(result, 2, bytes_of(dfs.data(), dfs.size() * 4));
  PyTuple_SET_ITEM(result, 3, bytes_of(post_offs.data(), post_offs.size() * 8));
  PyTuple_SET_ITEM(result, 4, bytes_of(post_lens.data(), post_lens.size() * 4));
  PyTuple_SET_ITEM(result, 5, bytes_of(ids_arena.data(), ids_arena.size() * 4));
  PyTuple_SET_ITEM(result, 6, bytes_of(tfs_arena.data(), tfs_arena.size() * 4));
  PyTuple_SET_ITEM(result, 7, bytes_of(norms.data(), norms.size() * 4));
  PyTuple_SET_ITEM(result, 8, PyLong_FromLongLong(b->total_tokens));
  if (b->with_positions) {
    PyTuple_SET_ITEM(result, 9, bytes_of(pos_offsets.data(),
                                         pos_offsets.size() * 8));
    PyTuple_SET_ITEM(result, 10, bytes_of(pos_data.data(),
                                          pos_data.size() * 4));
  } else {
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(result, 9, Py_None);
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(result, 10, Py_None);
  }
  return result;
}

// ---------------------------------------------------------------------------
// k-way term-dictionary merge — the merge hot loop
// (role of tantivy's segment merge driven by the reference MergeExecutor,
// merge_split_directories; array-level: postings are re-based and
// re-padded, never re-tokenized). Semantics mirror
// index/merge_arrays.py::_merge_inverted exactly.

struct MergeReader {
  const uint8_t* blob;
  const int64_t* term_offsets;  // n_terms + 1
  const int32_t* dfs;
  const int64_t* post_offs;
  const int32_t* ids;
  const int32_t* tfs;
  const int64_t* pos_offs;  // arena_len + 1, or nullptr
  const int32_t* pos_data;  // or nullptr
  int64_t n_terms;
  int64_t doc_offset;
  int64_t cursor;  // current term ordinal

  bool done() const { return cursor >= n_terms; }
  std::pair<const uint8_t*, size_t> term() const {
    int64_t lo = term_offsets[cursor], hi = term_offsets[cursor + 1];
    return {blob + lo, static_cast<size_t>(hi - lo)};
  }
};

inline int term_cmp(std::pair<const uint8_t*, size_t> a,
                    std::pair<const uint8_t*, size_t> b) {
  size_t n = std::min(a.second, b.second);
  int c = std::memcmp(a.first, b.first, n);
  if (c != 0) return c;
  return a.second < b.second ? -1 : (a.second > b.second ? 1 : 0);
}

// merge_inverted([(blob, term_offsets, dfs, post_offs, ids, tfs,
//                  pos_offs|None, pos_data|None, doc_offset), ...],
//                num_docs_padded, with_positions)
//   -> (blob, term_offsets, dfs, post_offs, post_lens, ids, tfs,
//       pos_offsets|None, pos_data|None)        -- bytes (LE arrays)
PyObject* py_merge_inverted(PyObject*, PyObject* args) {
  PyObject* readers_list;
  long long num_docs_padded;
  int with_positions;
  if (!PyArg_ParseTuple(args, "OLp", &readers_list, &num_docs_padded,
                        &with_positions))
    return nullptr;
  if (!PyList_Check(readers_list)) {
    PyErr_SetString(PyExc_TypeError, "merge_inverted expects a list");
    return nullptr;
  }
  Py_ssize_t n_readers = PyList_Size(readers_list);
  std::vector<MergeReader> readers(n_readers);
  std::vector<std::vector<Py_buffer>> held(n_readers);
  auto release_all = [&]() {
    for (auto& bufs : held)
      for (auto& buf : bufs) PyBuffer_Release(&buf);
  };
  for (Py_ssize_t i = 0; i < n_readers; ++i) {
    PyObject* tup = PyList_GetItem(readers_list, i);
    Py_buffer blob_b, toffs_b, dfs_b, poffs_b, ids_b, tfs_b;
    PyObject *pos_offs_o, *pos_data_o;
    long long doc_offset;
    if (!PyArg_ParseTuple(tup, "y*y*y*y*y*y*OOL", &blob_b, &toffs_b, &dfs_b,
                          &poffs_b, &ids_b, &tfs_b, &pos_offs_o, &pos_data_o,
                          &doc_offset)) {
      release_all();
      return nullptr;
    }
    held[i] = {blob_b, toffs_b, dfs_b, poffs_b, ids_b, tfs_b};
    MergeReader& r = readers[i];
    r.blob = static_cast<const uint8_t*>(blob_b.buf);
    r.term_offsets = static_cast<const int64_t*>(toffs_b.buf);
    r.dfs = static_cast<const int32_t*>(dfs_b.buf);
    r.post_offs = static_cast<const int64_t*>(poffs_b.buf);
    r.ids = static_cast<const int32_t*>(ids_b.buf);
    r.tfs = static_cast<const int32_t*>(tfs_b.buf);
    r.n_terms = dfs_b.len / 4;
    r.doc_offset = doc_offset;
    r.cursor = 0;
    r.pos_offs = nullptr;
    r.pos_data = nullptr;
    if (pos_offs_o != Py_None && pos_data_o != Py_None) {
      Py_buffer po_b, pd_b;
      if (PyObject_GetBuffer(pos_offs_o, &po_b, PyBUF_SIMPLE) != 0 ||
          (PyObject_GetBuffer(pos_data_o, &pd_b, PyBUF_SIMPLE) != 0 &&
           (PyBuffer_Release(&po_b), true))) {
        release_all();
        return nullptr;
      }
      held[i].push_back(po_b);
      held[i].push_back(pd_b);
      r.pos_offs = static_cast<const int64_t*>(po_b.buf);
      r.pos_data = static_cast<const int32_t*>(pd_b.buf);
    }
  }

  std::string blob;
  std::vector<int64_t> term_offsets{0};
  std::vector<int32_t> dfs;
  std::vector<int64_t> post_offs;
  std::vector<int32_t> post_lens;
  std::vector<int32_t> ids_arena;
  std::vector<int32_t> tfs_arena;
  std::vector<int64_t> pos_offsets;
  std::vector<int32_t> pos_data;

  Py_BEGIN_ALLOW_THREADS
  {
    // upper-bound reservations: repeated geometric growth of the arenas
    // would memcpy hundreds of MB; the bound is cheap and tight enough
    // (sum of input dfs + worst-case padding per distinct term)
    int64_t max_terms = 0, sum_df = 0, blob_bytes = 0, pos_bytes = 0;
    for (auto& r : readers) {
      max_terms += r.n_terms;
      blob_bytes += r.term_offsets[r.n_terms];
      for (int64_t t = 0; t < r.n_terms; ++t) sum_df += r.dfs[t];
      if (r.pos_offs != nullptr && r.n_terms > 0) {
        int64_t last = r.post_offs[r.n_terms - 1] + r.dfs[r.n_terms - 1];
        pos_bytes += r.pos_offs[last];
      }
    }
    int64_t max_padded = sum_df + max_terms * (kPostingPad - 1) + kPostingPad;
    blob.reserve(blob_bytes);
    term_offsets.reserve(max_terms + 1);
    dfs.reserve(max_terms);
    post_offs.reserve(max_terms);
    post_lens.reserve(max_terms);
    ids_arena.reserve(max_padded);
    tfs_arena.reserve(max_padded);
    if (with_positions) {
      pos_offsets.reserve(max_padded + 1);
      pos_data.reserve(pos_bytes);
    }
  }
  int64_t cursor = 0;
  int64_t pos_cursor = 0;
  std::vector<Py_ssize_t> group;
  for (;;) {
    // min term among the heads (k is small: linear scan beats a heap)
    Py_ssize_t first = -1;
    for (Py_ssize_t i = 0; i < n_readers; ++i) {
      if (readers[i].done()) continue;
      if (first < 0 || term_cmp(readers[i].term(), readers[first].term()) < 0)
        first = i;
    }
    if (first < 0) break;
    auto term = readers[first].term();
    group.clear();
    for (Py_ssize_t i = first; i < n_readers; ++i) {
      if (!readers[i].done() && term_cmp(readers[i].term(), term) == 0)
        group.push_back(i);  // ascending reader order == ascending doc ids
    }

    int64_t df = 0;
    for (Py_ssize_t i : group) df += readers[i].dfs[readers[i].cursor];
    int64_t padded = pad_to(std::max<int64_t>(df, 1), kPostingPad);
    size_t base = ids_arena.size();
    ids_arena.resize(base + padded, static_cast<int32_t>(num_docs_padded));
    tfs_arena.resize(base + padded, 0);
    if (with_positions) pos_offsets.resize(base + padded, 0);
    int64_t w = 0;
    for (Py_ssize_t i : group) {
      MergeReader& r = readers[i];
      int64_t lo = r.post_offs[r.cursor];
      int64_t rdf = r.dfs[r.cursor];
      // bulk copies: tfs memcpy; ids re-based in a vectorizable loop
      std::memcpy(tfs_arena.data() + base + w, r.tfs + lo, rdf * 4);
      const int32_t off = static_cast<int32_t>(r.doc_offset);
      int32_t* dst = ids_arena.data() + base + w;
      const int32_t* src = r.ids + lo;
      for (int64_t j = 0; j < rdf; ++j) dst[j] = src[j] + off;
      if (with_positions && r.pos_offs != nullptr) {
        int64_t plo = r.pos_offs[lo], phi = r.pos_offs[lo + rdf];
        int64_t* podst = pos_offsets.data() + base + w;
        const int64_t* posrc = r.pos_offs + lo;
        const int64_t shift = pos_cursor - plo;
        for (int64_t j = 0; j < rdf; ++j) podst[j] = posrc[j] + shift;
        pos_data.insert(pos_data.end(), r.pos_data + plo, r.pos_data + phi);
        pos_cursor += phi - plo;
      } else if (with_positions) {
        for (int64_t j = 0; j < rdf; ++j) pos_offsets[base + w + j] = pos_cursor;
      }
      w += rdf;
      ++r.cursor;
    }
    if (with_positions) {
      for (int64_t j = df; j < padded; ++j) pos_offsets[base + j] = pos_cursor;
    }
    blob.append(reinterpret_cast<const char*>(term.first), term.second);
    term_offsets.push_back(static_cast<int64_t>(blob.size()));
    dfs.push_back(static_cast<int32_t>(df));
    post_offs.push_back(cursor);
    post_lens.push_back(static_cast<int32_t>(padded));
    cursor += padded;
  }
  if (with_positions) pos_offsets.push_back(pos_cursor);  // trailing guard
  Py_END_ALLOW_THREADS

  release_all();
  auto bytes_of = [](const void* data, size_t nbytes) {
    return PyBytes_FromStringAndSize(static_cast<const char*>(data),
                                     static_cast<Py_ssize_t>(nbytes));
  };
  PyObject* result = PyTuple_New(9);
  PyTuple_SET_ITEM(result, 0, bytes_of(blob.data(), blob.size()));
  PyTuple_SET_ITEM(result, 1, bytes_of(term_offsets.data(),
                                       term_offsets.size() * 8));
  PyTuple_SET_ITEM(result, 2, bytes_of(dfs.data(), dfs.size() * 4));
  PyTuple_SET_ITEM(result, 3, bytes_of(post_offs.data(), post_offs.size() * 8));
  PyTuple_SET_ITEM(result, 4, bytes_of(post_lens.data(), post_lens.size() * 4));
  PyTuple_SET_ITEM(result, 5, bytes_of(ids_arena.data(), ids_arena.size() * 4));
  PyTuple_SET_ITEM(result, 6, bytes_of(tfs_arena.data(), tfs_arena.size() * 4));
  if (with_positions) {
    PyTuple_SET_ITEM(result, 7, bytes_of(pos_offsets.data(),
                                         pos_offsets.size() * 8));
    PyTuple_SET_ITEM(result, 8, bytes_of(pos_data.data(), pos_data.size() * 4));
  } else {
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(result, 7, Py_None);
    Py_INCREF(Py_None);
    PyTuple_SET_ITEM(result, 8, Py_None);
  }
  return result;
}

PyMethodDef kMethods[] = {
    {"new_builder", py_new_builder, METH_VARARGS,
     "new_builder(with_positions) -> capsule"},
    {"add_values", py_add_values, METH_VARARGS,
     "add_values(builder, doc_ids_i32, text_blob, offsets_i64)"},
    {"finish", py_finish, METH_VARARGS,
     "finish(builder, num_docs_padded) -> arrays tuple"},
    {"merge_inverted", py_merge_inverted, METH_VARARGS,
     "merge_inverted(readers, num_docs_padded, with_positions) -> arrays"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {PyModuleDef_HEAD_INIT, "fastindex",
                       "native inverted-index builder", -1, kMethods,
                       nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit_fastindex(void) { return PyModule_Create(&kModule); }
