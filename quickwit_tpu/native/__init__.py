"""Native extensions: compiled on first use, with pure-Python fallbacks.

The reference implements its entire indexing hot path natively (Rust/
tantivy); here the tokenize+postings-accumulation loop is a C++ CPython
extension (`fastindex.cpp`) compiled on demand with the baked-in g++.
`load_fastindex()` returns the module or None — callers must degrade to the
Python path, so a missing toolchain never breaks indexing, only slows it.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sysconfig
import threading
from typing import Any, Optional

logger = logging.getLogger(__name__)

# qwlint: disable-next-line=QW008 - one-time native-backend init lock; leaf by
# construction
_lock = threading.Lock()
_cached: Any = "unset"


def _build_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")


def _compile(src_basename: str = "fastindex.cpp",
             extra_flags: "tuple[str, ...]" = (),
             needs_python_include: bool = True) -> Optional[str]:
    """Mtime-cached on-demand g++ build shared by every native piece."""
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       src_basename)
    build_dir = _build_dir()
    os.makedirs(build_dir, exist_ok=True)
    so_path = os.path.join(
        build_dir, os.path.splitext(src_basename)[0] + ".so")
    if (os.path.exists(so_path)
            and os.path.getmtime(so_path) >= os.path.getmtime(src)):
        return so_path
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           *extra_flags]
    if needs_python_include:
        cmd.append(f"-I{sysconfig.get_paths()['include']}")
    cmd += [src, "-o", so_path + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(so_path + ".tmp", so_path)
        return so_path
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as exc:
        stderr = getattr(exc, "stderr", b"") or b""
        logger.warning("%s compilation failed, using Python fallback: %s %s",
                       src_basename, exc, stderr.decode()[:500])
        return None


def load_fastindex():
    """The compiled fastindex module, or None (Python fallback)."""
    global _cached
    if _cached != "unset":
        return _cached
    with _lock:
        if _cached != "unset":
            return _cached
        if os.environ.get("QW_DISABLE_NATIVE") == "1":
            _cached = None
            return None
        so_path = _compile()
        if so_path is None:
            _cached = None
            return None
        import importlib.util
        spec = importlib.util.spec_from_file_location("fastindex", so_path)
        try:
            module = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(module)  # type: ignore[union-attr]
            _cached = module
        except Exception as exc:  # noqa: BLE001 - load failure → fallback
            logger.warning("fastindex load failed: %s", exc)
            _cached = None
    return _cached


_leafbench_cached: Any = "unset"


def load_leafbench():
    """The compiled leafbench ctypes library (the benchmark's native CPU
    comparator, see leafbench.cpp), or None when g++ is unavailable or
    native code is disabled."""
    global _leafbench_cached
    if _leafbench_cached != "unset":
        return _leafbench_cached
    with _lock:
        if _leafbench_cached != "unset":
            return _leafbench_cached
        if os.environ.get("QW_DISABLE_NATIVE") == "1":
            _leafbench_cached = None
            return None
        so_path = _compile("leafbench.cpp", extra_flags=("-march=native",),
                           needs_python_include=False)
        if so_path is None:
            _leafbench_cached = None
            return None
        import ctypes
        try:
            lib = ctypes.CDLL(so_path)
        except OSError as exc:
            logger.warning("leafbench load failed: %s", exc)
            _leafbench_cached = None
            return None
        lib.leaf_term_aggs.restype = None
        lib.leaf_bool_range.restype = None
        _leafbench_cached = lib
        return lib
