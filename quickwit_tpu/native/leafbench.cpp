// Native CPU leaf-search comparator: the benchmark's honest denominator.
//
// Role: stand-in for the reference's tantivy leaf hot loop
// (`quickwit-search/src/leaf.rs:657-875`) which cannot be built in this
// image (no Rust toolchain). Implements the SAME leaf computation the TPU
// kernels run for posting-space term queries — BM25 scoring (tantivy
// k1=1.2, b=0.75), top-k, date-histogram and terms aggregation over fast
// columns — as a tight single-threaded C++ loop over the same memory
// layout the engine holds (padded postings + dense columns). This is a
// FAVORABLE CPU baseline: it reads pre-decoded, pre-ordinalized arrays
// with no posting decompression, no term-dictionary walk, and no
// document-store access, so a real tantivy leaf does strictly more work
// per query.
//
// Built on demand with the baked-in g++ (ctypes ABI, no Python API).

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {
constexpr float kK1 = 1.2f;
constexpr float kB = 0.75f;

// fixed-size min-heap on (score, -doc) — tantivy's TopCollector shape
struct Hit {
  float score;
  int32_t doc;
  bool operator<(const Hit& o) const {
    // heap of the WORST kept hit on top: higher score = better,
    // lower doc breaks ties (matches the engine's doc-asc tie-break)
    if (score != o.score) return score > o.score;
    return doc < o.doc;
  }
};

inline float Bm25(float tf, float norm, float inv_avg, float idf_gain) {
  const float denom = tf + kK1 * (1.0f - kB + kB * norm * inv_avg);
  return idf_gain * tf / std::max(denom, 1e-9f);
}

// Merge-advance a sorted posting list to `doc`; returns its tf or 0.
// Pad entries (ids < 0 or >= num_docs) never equal a real doc id.
inline float TfAt(const int32_t* ids, const int32_t* tfs, int64_t n,
                  int64_t* cursor, int32_t doc) {
  int64_t i = *cursor;
  while (i < n && ids[i] >= 0 && ids[i] < doc) ++i;
  *cursor = i;
  return (i < n && ids[i] == doc) ? static_cast<float>(tfs[i]) : 0.0f;
}
}  // namespace

extern "C" {

// One leaf search of a single-term query with optional aggregations.
//   ids/tfs:        padded posting arrays (pad entries: ids >= num_docs)
//   norms:          dense per-doc fieldnorm (token count)
//   ts_values/ts_present: histogram operand column (int64) or null
//   ord_col:        terms-agg ordinal column (-1 = missing) or null
//   k:              top-k size (0 = count/agg only, no scoring)
// Outputs (caller-allocated): hist_out[n_hist], terms_out[n_terms],
//   topk_scores/topk_docs[k], count_out[1].
void leaf_term_aggs(const int32_t* ids, const int32_t* tfs, int64_t n_post,
                    const int32_t* norms, int64_t num_docs,
                    const int64_t* ts_values, const uint8_t* ts_present,
                    int64_t origin, int64_t interval, int32_t n_hist,
                    const int32_t* ord_col, int32_t n_terms,
                    double idf, double avg_len, int32_t k,
                    int64_t* hist_out, int64_t* terms_out,
                    float* topk_scores, int32_t* topk_docs,
                    int64_t* count_out) {
  const float idf_gain = static_cast<float>(idf) * (kK1 + 1.0f);
  const float inv_avg = 1.0f / std::max(static_cast<float>(avg_len), 1e-9f);
  int64_t count = 0;

  std::vector<Hit> heap;
  heap.reserve(k > 0 ? k : 1);

  for (int64_t i = 0; i < n_post; ++i) {
    const int32_t doc = ids[i];
    if (doc < 0 || doc >= num_docs) continue;  // pad slot
    ++count;
    if (k > 0) {
      const float score = Bm25(static_cast<float>(tfs[i]),
                               static_cast<float>(norms[doc]),
                               inv_avg, idf_gain);
      if (static_cast<int32_t>(heap.size()) < k) {
        heap.push_back({score, doc});
        std::push_heap(heap.begin(), heap.end());
      } else if (Hit{score, doc} < heap.front()) {
        std::pop_heap(heap.begin(), heap.end());
        heap.back() = {score, doc};
        std::push_heap(heap.begin(), heap.end());
      }
    }
    if (n_hist > 0 && ts_present != nullptr && ts_present[doc]) {
      const int64_t idx = (ts_values[doc] - origin) / interval;
      if (idx >= 0 && idx < n_hist) ++hist_out[idx];
    }
    if (n_terms > 0 && ord_col != nullptr) {
      const int32_t ord = ord_col[doc];
      if (ord >= 0 && ord < n_terms) ++terms_out[ord];
    }
  }
  if (k > 0) {
    std::sort_heap(heap.begin(), heap.end());  // best-first
    for (size_t i = 0; i < heap.size(); ++i) {
      topk_scores[i] = heap[i].score;
      topk_docs[i] = heap[i].doc;
    }
    for (int32_t i = static_cast<int32_t>(heap.size()); i < k; ++i) {
      topk_scores[i] = -1.0f;
      topk_docs[i] = -1;
    }
  }
  *count_out = count;
}

// One leaf search of a boolean query: scored MUST term AND'ed with a
// timestamp range filter, plus up to two optional scored SHOULD terms
// (pure OR — they widen scores, never the match set). The c2 benchmark
// shape. All posting lists are doc-id-sorted with pads outside
// [0, num_docs); range bounds are INCLUSIVE and pre-resolved by the
// caller into the column's on-disk domain (raw values, or scaled deltas
// for FOR-packed columns — comparisons are domain-invariant).
//   must_*:   scored conjunctive term postings + its field's norms
//   s1_*/s2_*: should-term postings (n == 0 disables a slot); both share
//              one field (norms + avg_len), per-term idf
//   ts_*:     range operand column (int64) + present bytes
// Outputs (caller-allocated): topk_scores/topk_docs[k], count_out[1].
void leaf_bool_range(const int32_t* must_ids, const int32_t* must_tfs,
                     int64_t n_must, const int32_t* must_norms,
                     double must_idf, double must_avg_len,
                     const int32_t* s1_ids, const int32_t* s1_tfs,
                     int64_t n_s1,
                     const int32_t* s2_ids, const int32_t* s2_tfs,
                     int64_t n_s2,
                     const int32_t* should_norms, double s1_idf,
                     double s2_idf, double should_avg_len,
                     const int64_t* ts_values, const uint8_t* ts_present,
                     int64_t lo, int64_t hi,
                     int64_t num_docs, int32_t k,
                     float* topk_scores, int32_t* topk_docs,
                     int64_t* count_out) {
  const float must_gain = static_cast<float>(must_idf) * (kK1 + 1.0f);
  const float s1_gain = static_cast<float>(s1_idf) * (kK1 + 1.0f);
  const float s2_gain = static_cast<float>(s2_idf) * (kK1 + 1.0f);
  const float must_inv_avg =
      1.0f / std::max(static_cast<float>(must_avg_len), 1e-9f);
  const float should_inv_avg =
      1.0f / std::max(static_cast<float>(should_avg_len), 1e-9f);
  int64_t count = 0;
  int64_t c1 = 0, c2 = 0;  // merge cursors into the should lists

  std::vector<Hit> heap;
  heap.reserve(k > 0 ? k : 1);

  for (int64_t i = 0; i < n_must; ++i) {
    const int32_t doc = must_ids[i];
    if (doc < 0 || doc >= num_docs) continue;  // pad slot
    if (!ts_present[doc]) continue;
    const int64_t v = ts_values[doc];
    if (v < lo || v > hi) continue;
    ++count;
    if (k <= 0) continue;
    float score = Bm25(static_cast<float>(must_tfs[i]),
                       static_cast<float>(must_norms[doc]),
                       must_inv_avg, must_gain);
    const float snorm = static_cast<float>(should_norms[doc]);
    const float tf1 = TfAt(s1_ids, s1_tfs, n_s1, &c1, doc);
    if (tf1 > 0.0f) score += Bm25(tf1, snorm, should_inv_avg, s1_gain);
    const float tf2 = TfAt(s2_ids, s2_tfs, n_s2, &c2, doc);
    if (tf2 > 0.0f) score += Bm25(tf2, snorm, should_inv_avg, s2_gain);
    if (static_cast<int32_t>(heap.size()) < k) {
      heap.push_back({score, doc});
      std::push_heap(heap.begin(), heap.end());
    } else if (Hit{score, doc} < heap.front()) {
      std::pop_heap(heap.begin(), heap.end());
      heap.back() = {score, doc};
      std::push_heap(heap.begin(), heap.end());
    }
  }
  if (k > 0) {
    std::sort_heap(heap.begin(), heap.end());  // best-first
    for (size_t i = 0; i < heap.size(); ++i) {
      topk_scores[i] = heap[i].score;
      topk_docs[i] = heap[i].doc;
    }
    for (int32_t i = static_cast<int32_t>(heap.size()); i < k; ++i) {
      topk_scores[i] = -1.0f;
      topk_docs[i] = -1;
    }
  }
  *count_out = count;
}

}  // extern "C"
