"""Datetime parsing/formatting.

Role of the reference's `quickwit-datetime` crate: parse input datetime values
in several formats (RFC3339, unix timestamps at several resolutions, strptime
patterns) into a single index representation. We store **microseconds since
unix epoch (i64)**, matching the reference's `DateTime` precision ladder.
"""

from __future__ import annotations

import datetime as _dt
import re
from typing import Union

_RFC3339_RE = re.compile(
    r"^(\d{4})-(\d{2})-(\d{2})[Tt ](\d{2}):(\d{2}):(\d{2})(\.\d+)?"
    r"(?:([Zz])|([+-]\d{2}):?(\d{2}))?$"
)
_DATE_RE = re.compile(r"^(\d{4})[-/](\d{2})[-/](\d{2})$")

MICROS = 1_000_000


def _unix_number_to_micros(value: float) -> int:
    """Heuristic resolution detection for numeric timestamps.

    Mirrors the reference's `unix_timestamp` coercion: seconds, millis,
    micros, or nanos chosen by magnitude.
    """
    v = abs(value)
    if v < 10_000_000_000:  # seconds (until year ~2286)
        return int(round(value * MICROS))
    if v < 10_000_000_000_000:  # millis
        return int(round(value * 1_000))
    if v < 10_000_000_000_000_000:  # micros
        return int(round(value))
    return int(round(value / 1_000))  # nanos


def parse_datetime_to_micros(
    value: Union[str, int, float],
    input_formats: tuple[str, ...] = ("rfc3339", "unix_timestamp"),
) -> int:
    """Parse per the configured input formats, first match wins."""
    for fmt in input_formats:
        try:
            if fmt == "unix_timestamp":
                if isinstance(value, (int, float)) and not isinstance(value, bool):
                    return _unix_number_to_micros(value)
                if isinstance(value, str) and re.fullmatch(r"-?\d+", value):
                    # query-string bounds arrive as strings
                    # (reference: `ts:>=1684993002`)
                    return _unix_number_to_micros(int(value))
                continue
            if fmt in ("rfc3339", "iso8601"):
                if not isinstance(value, str):
                    continue
                micros = _parse_rfc3339(value)
                if micros is not None:
                    return micros
                continue
            # strptime pattern
            if isinstance(value, str):
                dt = _dt.datetime.strptime(value, fmt)
                if dt.tzinfo is None:
                    dt = dt.replace(tzinfo=_dt.timezone.utc)
                return int(dt.timestamp() * MICROS)
        except (ValueError, OverflowError):
            continue
    raise ValueError(f"cannot parse datetime {value!r} with formats {input_formats}")


def _parse_rfc3339(text: str) -> int | None:
    m = _RFC3339_RE.match(text.strip())
    if m is None:
        dm = _DATE_RE.match(text.strip())
        if dm is None:
            return None
        dt = _dt.datetime(int(dm[1]), int(dm[2]), int(dm[3]), tzinfo=_dt.timezone.utc)
        return int(dt.timestamp() * MICROS)
    frac = m.group(7)
    micros_frac = int(round(float(frac) * MICROS)) if frac else 0
    if m.group(8):  # Z
        offset = _dt.timezone.utc
    elif m.group(9):
        sign = 1 if m.group(9).startswith("+") else -1
        hours = int(m.group(9)[1:])
        minutes = int(m.group(10))
        offset = _dt.timezone(sign * _dt.timedelta(hours=hours, minutes=minutes))
    else:
        offset = _dt.timezone.utc
    dt = _dt.datetime(
        int(m[1]), int(m[2]), int(m[3]), int(m[4]), int(m[5]), int(m[6]), tzinfo=offset
    )
    return int(dt.timestamp()) * MICROS + micros_frac


def format_micros_rfc3339(micros: int) -> str:
    dt = _dt.datetime.fromtimestamp(micros / MICROS, tz=_dt.timezone.utc)
    if micros % MICROS == 0:
        # reference Rfc3339 output drops zero subseconds
        return dt.strftime("%Y-%m-%dT%H:%M:%S") + "Z"
    return dt.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


def truncate_to_precision(micros: int, precision: "str | None") -> int:
    """Truncate microseconds to a fast-column precision (reference
    `fast_precision`): both stored values and range bounds truncate, so
    sub-precision range bounds behave exactly like the reference."""
    if precision == "seconds":
        return (micros // 1_000_000) * 1_000_000
    if precision == "milliseconds":
        return (micros // 1_000) * 1_000
    return micros


_JAVA_TIME_TOKENS = [
    ("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
    ("mm", "%M"), ("ss", "%S"), ("SSSSSS", "%f"), ("SSS", "%f"),
]


def parse_java_time_format(pattern: str, text: str) -> int:
    """Parse `text` with an ES/java-time `format` pattern (range queries'
    `format` param; reference: quickwit-datetime's java-time support).
    Supports the yyyy/MM/dd/HH/mm/ss/SSS[SSS] tokens and quoted literals."""
    import datetime as _dt
    fmt = ""
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "'":
            end = pattern.find("'", i + 1)
            if end == -1:
                raise ValueError(f"unterminated quote in format {pattern!r}")
            fmt += pattern[i + 1: end].replace("%", "%%")
            i = end + 1
            continue
        for token, directive in _JAVA_TIME_TOKENS:
            if pattern.startswith(token, i):
                fmt += directive
                i += len(token)
                break
        else:
            fmt += ch.replace("%", "%%")
            i += 1
    parsed = _dt.datetime.strptime(text, fmt).replace(
        tzinfo=_dt.timezone.utc)
    return int(parsed.timestamp()) * 1_000_000 + parsed.microsecond
