"""Persistent XLA compilation cache.

The flagship leaf-search program costs ~48s to compile on a TPU backend
(BENCH_r02 warmup) — paying that once per *process* makes first-query
latency a minute. JAX's persistent compilation cache keys executables by
HLO fingerprint, so every process after the first loads the compiled
binary in milliseconds. The reference has no analogue (tantivy is
interpreted); this is TPU-build-specific operability.

Enabled by default for servers and benches; set QW_COMPILE_CACHE=0 to
disable or QW_COMPILE_CACHE_DIR to relocate.
"""

from __future__ import annotations

import os

_ENABLED = False


def enable_persistent_compile_cache(path: "str | None" = None) -> "str | None":
    """Idempotently point JAX's compilation cache at a durable directory.
    Returns the cache dir, or None when disabled/unsupported."""
    global _ENABLED
    if os.environ.get("QW_COMPILE_CACHE", "1") in ("0", "false"):
        return None
    cache_dir = (path or os.environ.get("QW_COMPILE_CACHE_DIR")
                 or os.path.expanduser("~/.cache/quickwit_tpu/xla"))
    if _ENABLED:
        return cache_dir
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: the steady state is many small
        # per-signature executables
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        _ENABLED = True
        return cache_dir
    except Exception:  # noqa: BLE001 - cache is an optimization, never fatal
        return None
