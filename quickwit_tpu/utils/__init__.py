from .datetime_utils import parse_datetime_to_micros, format_micros_rfc3339

__all__ = ["parse_datetime_to_micros", "format_micros_rfc3339"]
