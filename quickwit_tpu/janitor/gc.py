"""Garbage collection of dead splits.

Role of the reference's `GarbageCollector` actor
(`quickwit-janitor/src/actors/garbage_collector.rs:104`) and
`quickwit-index-management/src/garbage_collection.rs`:
- staged splits older than a grace period (upload presumed crashed) are
  deleted from the metastore and storage,
- marked-for-deletion splits past a grace period have their files deleted
  then their metastore entries removed,
- orphan split files with no metastore entry are removed.
"""

from __future__ import annotations

import logging
import time

from ..metastore.base import ListSplitsQuery, Metastore
from ..models.split_metadata import SplitState
from ..storage.base import StorageResolver

logger = logging.getLogger(__name__)

STAGED_GRACE_SECS = 2 * 3600       # reference: staged grace period
DELETION_GRACE_SECS = 120           # reference: 2 min


def run_garbage_collection(metastore: Metastore, storage_resolver: StorageResolver,
                           staged_grace_secs: int = STAGED_GRACE_SECS,
                           deletion_grace_secs: int = DELETION_GRACE_SECS,
                           now: float | None = None) -> dict[str, int]:
    now_ts = now if now is not None else time.time()
    removed_files = 0
    removed_entries = 0
    removed_orphans = 0
    for index_metadata in metastore.list_indexes():
        index_uid = index_metadata.index_uid
        storage = storage_resolver.resolve(index_metadata.index_config.index_uri)
        removed_orphans += _delete_orphan_files(metastore, storage, index_uid)
        stale_staged = [
            s for s in metastore.list_splits(ListSplitsQuery(
                index_uids=[index_uid], states=[SplitState.STAGED]))
            if now_ts - s.update_timestamp > staged_grace_secs
        ]
        if stale_staged:
            metastore.mark_splits_for_deletion(
                index_uid, [s.metadata.split_id for s in stale_staged])
        to_delete = [
            s for s in metastore.list_splits(ListSplitsQuery(
                index_uids=[index_uid], states=[SplitState.MARKED_FOR_DELETION]))
            if now_ts - s.update_timestamp > deletion_grace_secs
        ]
        if not to_delete:
            continue
        split_ids = [s.metadata.split_id for s in to_delete]
        for split_id in split_ids:
            try:
                storage.delete(f"{split_id}.split")
                removed_files += 1
            except Exception:  # noqa: BLE001 - already gone is success
                pass
        metastore.delete_splits(index_uid, split_ids)
        removed_entries += len(split_ids)
        logger.info("gc removed %d splits of %s", len(split_ids), index_uid)
    return {"gc_deleted_files": removed_files,
            "gc_deleted_splits": removed_entries,
            "gc_deleted_orphans": removed_orphans}


def _delete_orphan_files(metastore: Metastore, storage,
                         index_uid: str) -> int:
    """Remove `.split` files with NO metastore entry in ANY state
    (reference `garbage_collection.rs:1` orphan cleanup). Safe without a
    grace period because of two orderings:
    - every upload path stages its metastore entry BEFORE the storage put
      (uploader/merge protocol), and
    - the file listing is taken BEFORE a forced metastore refresh, so any
      file in the listing had its stage committed before the state we
      compare against was read (a cached, minutes-old metastore view
      could otherwise miss another node's fresh stage and delete a live
      upload).
    A file with no entry can then only be the debris of a crashed upload
    whose staged entry was already GC'd, or of a delete_splits whose file
    removal failed."""
    try:
        files = storage.list_files()
    except Exception as exc:  # noqa: BLE001 - listing is best-effort
        logger.debug("orphan scan listing failed for %s: %s",
                     index_uid, exc)
        return 0
    metastore.refresh()
    known = {
        s.metadata.split_id
        for s in metastore.list_splits(ListSplitsQuery(
            index_uids=[index_uid]))
    }
    removed = 0
    for name in files:
        if not name.endswith(".split"):
            continue
        split_id = name[: -len(".split")]
        if split_id in known:
            continue
        try:
            storage.delete(name)
            removed += 1
        except Exception:  # noqa: BLE001 - already gone is success
            pass
    if removed:
        logger.info("gc removed %d orphan files of %s", removed, index_uid)
    return removed
