"""Delete-task planner: schedules delete-applying merges.

Role of the reference's `DeleteTaskPlanner`
(`quickwit-janitor/src/actors/delete_task_planner.rs:75`): find published
splits whose `delete_opstamp` lags the index's latest delete task, probe
each with a COUNT search for the pending delete queries, and

- splits with zero matching docs get their `delete_opstamp` fast-forwarded
  in place (no rewrite — the reference does exactly this to keep GDPR
  sweeps cheap on untouched splits),
- splits with matching docs are rewritten through the normal merge
  protocol (`MergeExecutor.execute` with the pending tasks), which
  publishes the replacement atomically and stamps it with the latest
  opstamp.

One planner pass is idempotent: rerunning converges to every published
split carrying `last_delete_opstamp`.
"""

from __future__ import annotations

import logging

from ..indexing.merge import MergeExecutor, MergeOperation
from ..metastore.base import ListSplitsQuery, Metastore
from ..models.doc_mapper import DocMapper
from ..models.split_metadata import SplitState
from ..storage.base import Storage

logger = logging.getLogger(__name__)

# bound per pass (reference plans a small batch per activation so one huge
# backlog cannot starve regular merges)
MAX_REWRITES_PER_PASS = 16


class DeleteTaskPlanner:
    def __init__(self, index_uid: str, doc_mapper: DocMapper,
                 metastore: Metastore, split_storage: Storage,
                 node_id: str = "node-0"):
        self.index_uid = index_uid
        self.doc_mapper = doc_mapper
        self.metastore = metastore
        self.split_storage = split_storage
        self.executor = MergeExecutor(index_uid, doc_mapper, metastore,
                                      split_storage, node_id=node_id)

    def run_pass(self, max_rewrites: int = MAX_REWRITES_PER_PASS
                 ) -> dict[str, int]:
        """One planning pass; returns counters for observability/tests."""
        last_opstamp = self.metastore.last_delete_opstamp(self.index_uid)
        stale = [
            s for s in self.metastore.list_splits(ListSplitsQuery(
                index_uids=[self.index_uid],
                states=[SplitState.PUBLISHED]))
            if s.metadata.delete_opstamp < last_opstamp
        ]
        # oldest opstamp first: the most-behind splits carry the most
        # pending deletes and bound the sweep's convergence
        stale.sort(key=lambda s: s.metadata.delete_opstamp)
        # ONE task fetch per pass (backends return tasks with opstamp
        # strictly greater than opstamp_start); filtered per split in
        # memory instead of a metastore query per split
        all_tasks = self.metastore.list_delete_tasks(
            self.index_uid,
            opstamp_start=stale[0].metadata.delete_opstamp) if stale else []
        fast_forwarded: list[str] = []
        rewritten = 0
        for split in stale:
            if rewritten >= max_rewrites:
                break
            tasks = [t for t in all_tasks
                     if t["opstamp"] > split.metadata.delete_opstamp]
            if not tasks:
                fast_forwarded.append(split.metadata.split_id)
                continue
            if not self._split_matches_any(split, tasks):
                fast_forwarded.append(split.metadata.split_id)
                continue
            try:
                self.executor.execute(MergeOperation(splits=(split,)),
                                      delete_tasks=tasks)
                rewritten += 1
            except Exception as exc:  # noqa: BLE001 - next pass retries
                logger.warning("delete merge of %s failed: %s",
                               split.metadata.split_id, exc)
        if fast_forwarded:
            self.metastore.update_splits_delete_opstamp(
                self.index_uid, fast_forwarded, last_opstamp)
        return {"delete_splits_rewritten": rewritten,
                "delete_splits_fast_forwarded": len(fast_forwarded),
                "delete_splits_pending": max(
                    0, len(stale) - rewritten - len(fast_forwarded))}

    def _split_matches_any(self, split, tasks: list[dict]) -> bool:
        """COUNT probe: does any pending delete query hit this split?
        (reference probes with a search before scheduling the rewrite)"""
        from ..index.reader import SplitReader
        from ..indexing.pipeline import split_file_path
        from ..query.ast import ast_from_dict
        from ..search.leaf import leaf_search_single_split
        from ..search.models import SearchRequest
        try:
            reader = SplitReader(self.split_storage,
                                 split_file_path(split.metadata.split_id))
        except Exception as exc:  # noqa: BLE001 - treat as matching
            logger.debug("delete probe open failed for %s: %s",
                         split.metadata.split_id, exc)
            return True  # rewrite path will surface the real error
        for task in tasks:
            try:
                response = leaf_search_single_split(
                    SearchRequest(index_ids=[self.index_uid],
                                  query_ast=ast_from_dict(task["query_ast"]),
                                  max_hits=0),
                    self.doc_mapper, reader, split.metadata.split_id)
            except Exception as exc:  # noqa: BLE001 - treat as matching
                logger.debug("delete probe failed for %s: %s",
                             split.metadata.split_id, exc)
                return True
            if response.num_hits > 0:
                return True
        return False


def run_delete_planner(metastore: Metastore, storage_resolver,
                       node_id: str = "node-0") -> dict[str, int]:
    """Planner pass over every index (the janitor entry point)."""
    totals = {"delete_splits_rewritten": 0,
              "delete_splits_fast_forwarded": 0,
              "delete_splits_pending": 0}
    for index_metadata in metastore.list_indexes():
        if metastore.last_delete_opstamp(index_metadata.index_uid) == 0:
            continue  # no delete tasks ever created for this index
        doc_mapper = index_metadata.index_config.doc_mapper
        storage = storage_resolver.resolve(
            index_metadata.index_config.index_uri)
        planner = DeleteTaskPlanner(
            index_metadata.index_uid, doc_mapper, metastore, storage,
            node_id=node_id)
        stats = planner.run_pass()
        for key, value in stats.items():
            totals[key] += value
    return totals
