from .gc import run_garbage_collection
from .retention import apply_retention

__all__ = ["run_garbage_collection", "apply_retention"]
