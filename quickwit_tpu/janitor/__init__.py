from .delete_planner import DeleteTaskPlanner, run_delete_planner
from .gc import run_garbage_collection
from .retention import apply_retention

__all__ = ["DeleteTaskPlanner", "run_delete_planner",
           "run_garbage_collection", "apply_retention"]
