"""Retention policy enforcement.

Role of the reference's `RetentionPolicyExecutor`
(`quickwit-janitor/src/actors/retention_policy_executor.rs:60`): splits whose
entire time range is older than the index's retention period are marked for
deletion (GC then removes them).
"""

from __future__ import annotations

import logging
import time

from ..metastore.base import ListSplitsQuery, Metastore
from ..models.split_metadata import SplitState

logger = logging.getLogger(__name__)


def apply_retention(metastore: Metastore, now: float | None = None) -> dict[str, int]:
    now_ts = now if now is not None else time.time()
    marked = 0
    for index_metadata in metastore.list_indexes():
        retention = index_metadata.index_config.retention
        if retention is None:
            continue
        cutoff_micros = int((now_ts - retention.period_seconds) * 1_000_000)
        expired = [
            s for s in metastore.list_splits(ListSplitsQuery(
                index_uids=[index_metadata.index_uid],
                states=[SplitState.PUBLISHED]))
            if s.metadata.time_range_end is not None
            and s.metadata.time_range_end < cutoff_micros
        ]
        if expired:
            metastore.mark_splits_for_deletion(
                index_metadata.index_uid,
                [s.metadata.split_id for s in expired])
            marked += len(expired)
            logger.info("retention marked %d splits of %s",
                        len(expired), index_metadata.index_uid)
    return {"retention_marked_splits": marked}
