"""Benchmark: the five BASELINE.json leaf-search configs on one real chip.

Per config this measures, after warmup:
- `e2e_ms`   p50 single-query end-to-end latency (host lowering + cached
             device arrays + jitted kernel + ONE batched readback). Under
             the axon tunnel this includes a full host↔device RTT.
- `pipe_ms`  effective per-query latency with PIPELINE_DEPTH queries in
             flight: dispatch i+1 before reading back i, with async
             device→host copies — the serving-throughput number; tunnel
             RTTs amortize across in-flight queries.
- `dev_ms`   on-device execution time per query, measured by running the
             kernel N deep inside one `lax.fori_loop` dispatch at two
             depths and differencing ((t(n2)-t(n1))/(n2-n1)) so constant
             dispatch/readback overhead cancels exactly.
- `hbm_gbps` + `bw_util`: estimated HBM bytes the plan touches per query
             (posting-space plans touch postings fully + gather columns at
             P positions; dense plans read every plan array) / dev_ms,
             against the chip's peak HBM bandwidth.
- `cpu_ms`   the same workload on this package's CPU path (subprocess),
             the measured vs_baseline denominator per BASELINE.json; the
             reference tantivy binary cannot be built here (no Rust
             toolchain — see BASELINE.md).

Reference hot box these numbers stand against:
`quickwit-search/src/leaf.rs:657-875` (leaf_search_single_split).

Prints ONE driver-facing JSON line (the north-star hdfs-logs
term+date_histogram config) on stdout; per-config JSON lines go to stderr
and the full table to BENCH_DETAILS.json.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_DOCS = int(os.environ.get("BENCH_NUM_DOCS", 10_000_000))
SO_DOCS = int(os.environ.get("BENCH_SO_DOCS", 5_000_000))
# config #5: many-split fused dispatch. 64 splits x 512k docs (33.5M docs
# total) per the round-4 directive — real split sizes, not 4096-doc
# micro-splits; all splits still execute as ONE vmapped XLA program.
OTEL_SPLITS = int(os.environ.get("BENCH_OTEL_SPLITS", 64))
OTEL_DOCS = int(os.environ.get("BENCH_OTEL_DOCS", 524_288))
ITERATIONS = int(os.environ.get("BENCH_ITERS", 20))
PIPELINE_DEPTH = int(os.environ.get("BENCH_PIPELINE_DEPTH", 8))
PIPELINE_QUERIES = int(os.environ.get("BENCH_PIPELINE_QUERIES", 48))
# concurrent queries per dispatch on the pipelined path (the serving
# QueryBatcher's shape, search/batcher.py): measured on the real chip,
# every dispatch round through the axon tunnel costs a fixed ~60-65 ms
# that pipelining depth cannot amortize (tools/profile_tunnel.py), while
# batched queries inside one dispatch run at device speed — the same
# reason the reference batches leaf requests per node (leaf.rs:81)
PIPELINE_BATCH = int(os.environ.get("BENCH_PIPELINE_BATCH", 16))
DEV_DEPTHS = (8, 40)
DEVICE_TIMEOUT_SECS = int(os.environ.get("BENCH_DEVICE_TIMEOUT", 180))
PROBE_DEADLINE_SECS = int(os.environ.get("BENCH_PROBE_DEADLINE", 60))
PROBE_BACKOFF_SECS = float(os.environ.get("BENCH_PROBE_BACKOFF", 5))

# peak HBM bandwidth by device kind (GB/s); the utilization denominator
_PEAK_HBM = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,   # v5e
    "TPU v5": 2765e9,       # v5p
    "TPU v6 lite": 1640e9,  # v6e / Trillium
}


def _probe_device_once(deadline: float) -> "str | None":
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=deadline)
    except subprocess.TimeoutExpired:
        print(f"# device probe: no response within {deadline:.0f}s "
              "(tunnel wedged or still initializing)", file=sys.stderr)
        return None
    if probe.returncode == 0:
        return probe.stdout.decode().strip().splitlines()[-1]
    print(f"# device probe failed rc={probe.returncode}: "
          f"{probe.stderr.decode()[-200:]}", file=sys.stderr)
    return None


def _ensure_device_or_fall_back() -> str:
    if os.environ.get("QW_JAX_PLATFORM"):
        return os.environ["QW_JAX_PLATFORM"]
    budget_end = time.monotonic() + DEVICE_TIMEOUT_SECS
    attempt = 0
    while time.monotonic() < budget_end:
        attempt += 1
        remaining = budget_end - time.monotonic()
        deadline = min(PROBE_DEADLINE_SECS, max(remaining, 5.0))
        platform = _probe_device_once(deadline)
        if platform is not None:
            print(f"# device probe: {platform} (attempt {attempt})",
                  file=sys.stderr)
            return platform
        if time.monotonic() + PROBE_BACKOFF_SECS >= budget_end:
            break
        time.sleep(PROBE_BACKOFF_SECS)
    print(f"# device init failed after {attempt} probe(s) within "
          f"{DEVICE_TIMEOUT_SECS}s; falling back to CPU", file=sys.stderr)
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)],
              {**os.environ, "QW_JAX_PLATFORM": "cpu",
               "BENCH_PLATFORM_NOTE": "cpu-fallback"})
    return "unreachable"


# --------------------------------------------------------------------------
# workloads


def _cached_split_bytes(tag: str, build) -> bytes:
    """Disk cache for generated benchmark splits: the CPU comparison
    child regenerates IDENTICAL corpora (same seeds) — at the realistic
    corpus scale (100k vocab, 20 tokens/doc, 10M docs) generation costs
    minutes, so parent and child share the bytes through .bench_cache.
    The cache key carries the generator parameters, so changing them
    invalidates naturally."""
    from quickwit_tpu.index.synthetic import (
        _BODY_TOKENS_PER_DOC, _BODY_VOCAB_SIZE, _SO_TOKENS_PER_DOC,
        _SO_VOCAB_SIZE)
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_cache")
    os.makedirs(cache_dir, exist_ok=True)
    # the key also hashes the generator SOURCE, so any change to the
    # synthetic corpus code (distribution knobs, split format emitted by
    # the builders) invalidates stale cached bytes
    import hashlib
    import quickwit_tpu.index.synthetic as _synth_mod
    with open(_synth_mod.__file__, "rb") as fh:
        gen_hash = hashlib.md5(fh.read()).hexdigest()[:10]
    params = (f"{tag}-v{_BODY_VOCAB_SIZE}x{_BODY_TOKENS_PER_DOC}"
              f"-s{_SO_VOCAB_SIZE}x{_SO_TOKENS_PER_DOC}-g{gen_hash}")
    path = os.path.join(cache_dir, f"{params}.split")
    if os.path.exists(path):
        with open(path, "rb") as fh:
            return fh.read()
    data = build()
    with open(path + ".tmp", "wb") as fh:
        fh.write(data)
    os.replace(path + ".tmp", path)
    return data


def _hdfs_reader(num_docs: int, seed: int = 7):
    from quickwit_tpu.common.uri import Uri
    from quickwit_tpu.index.reader import SplitReader
    from quickwit_tpu.index.synthetic import synthetic_hdfs_split
    from quickwit_tpu.storage.ram import RamStorage
    storage = RamStorage(Uri.parse("ram:///bench"))
    storage.put("hdfs.split", _cached_split_bytes(
        f"hdfs-{num_docs}-{seed}",
        lambda: synthetic_hdfs_split(num_docs, seed=seed)))
    return SplitReader(storage, "hdfs.split")


def _so_reader(num_docs: int, seed: int = 11):
    from quickwit_tpu.common.uri import Uri
    from quickwit_tpu.index.reader import SplitReader
    from quickwit_tpu.index.synthetic import synthetic_stackoverflow_split
    from quickwit_tpu.storage.ram import RamStorage
    storage = RamStorage(Uri.parse("ram:///bench"))
    storage.put("so.split", _cached_split_bytes(
        f"so-{num_docs}-{seed}",
        lambda: synthetic_stackoverflow_split(num_docs, seed=seed)))
    return SplitReader(storage, "so.split")


def _workloads():
    """name → (request, mapper, reader_thunk). Configs cite
    BASELINE.json.configs 1:1; `flagship` is the round-2-comparable
    north-star workload (term + top-10 + date_histogram + terms)."""
    from quickwit_tpu.index.synthetic import (
        HDFS_MAPPER, SO_MAPPER, body_term, so_term)
    from quickwit_tpu.query.ast import Bool, FullText, Range, RangeBound, Term
    from quickwit_tpu.search.models import SearchRequest

    day_us = 86400 * 1_000_000
    t0_us = 1_600_000_000 * 1_000_000
    return {
        "c1_term_top10": (SearchRequest(
            index_ids=["hdfs-logs"],
            query_ast=Term("severity_text", "ERROR"), max_hits=10,
        ), HDFS_MAPPER, lambda: _hdfs_reader(NUM_DOCS)),
        "c2_bool_range_top100": (SearchRequest(
            index_ids=["hdfs-logs"],
            query_ast=Bool(
                must=(Term("severity_text", "ERROR"),),
                should=(Term("body", body_term(3)), Term("body", body_term(7))),
                filter=(Range("timestamp",
                              lower=RangeBound(t0_us + day_us, True),
                              upper=RangeBound(t0_us + 4 * day_us, False)),),
            ), max_hits=100,
        ), HDFS_MAPPER, lambda: _hdfs_reader(NUM_DOCS)),
        "c3_agg_only": (SearchRequest(
            index_ids=["hdfs-logs"],
            query_ast=Term("severity_text", "ERROR"), max_hits=0,
            aggs={"over_time": {"date_histogram": {
                      "field": "timestamp", "fixed_interval": "1d"}},
                  "severities": {"terms": {"field": "severity_text",
                                           "size": 10}}},
        ), HDFS_MAPPER, lambda: _hdfs_reader(NUM_DOCS)),
        "c4_phrase_bm25_top20": (SearchRequest(
            index_ids=["stackoverflow"],
            query_ast=FullText("body", f"{so_term(10)} {so_term(11)}",
                               mode="phrase"),
            max_hits=20,
        ), SO_MAPPER, lambda: _so_reader(SO_DOCS)),
        "flagship": (SearchRequest(
            index_ids=["hdfs-logs"],
            query_ast=Term("severity_text", "ERROR"), max_hits=10,
            aggs={"over_time": {"date_histogram": {
                      "field": "timestamp", "fixed_interval": "1d"}},
                  "severities": {"terms": {"field": "severity_text",
                                           "size": 10}}},
        ), HDFS_MAPPER, lambda: _hdfs_reader(NUM_DOCS)),
    }


# --------------------------------------------------------------------------
# measurement primitives


def _estimate_bytes(plan) -> int:
    """HBM bytes one query reads. Posting-space plans read the postings
    arrays fully and gather per-doc slots at P positions; dense plans read
    every plan array once."""
    from quickwit_tpu.search import executor as ex
    total = sum(int(a.nbytes) for a in plan.arrays)
    if not ex._posting_space_eligible(plan):
        return total
    num_postings = plan.arrays[plan.root.ids_slot].shape[0]
    touched = 0
    for key, arr in zip(plan.array_keys, plan.arrays):
        if arr.ndim == 1 and arr.shape[0] >= plan.num_docs_padded:
            touched += num_postings * arr.dtype.itemsize  # gathered
        else:
            touched += int(arr.nbytes)
    return min(touched, total)


def _percentile(samples, q) -> float:
    samples = sorted(samples)
    return samples[min(len(samples) - 1, int(len(samples) * q))]


def _native_cpu_leaf(plan, request, reference_count: int,
                     iters: int) -> "dict | None":
    """Single-threaded C++ comparator (native/leafbench.cpp): the same
    leaf computation over the same arrays, standing in for the reference
    tantivy leaf (no Rust toolchain in-image — BASELINE.md). Returns p50
    ms, or None when the plan shape is outside the comparator's scope
    (posting-space term query + optional date_histogram/terms aggs).
    The comparator is a FAVORABLE CPU baseline: pre-decoded postings,
    pre-ordinalized columns, no doc-store work."""
    import ctypes

    import numpy as np
    from quickwit_tpu.native import load_leafbench
    from quickwit_tpu.search import executor as ex
    from quickwit_tpu.search.plan import BucketAggExec, PPostings

    lib = load_leafbench()
    if lib is None or not isinstance(plan.root, PPostings) \
            or not ex._posting_space_eligible(plan):
        return None
    if not plan.array_keys[plan.root.ids_slot].startswith("post."):
        # phrase/precomputed postings ("pre."): the CPU would owe extra
        # position-intersection work the comparator doesn't model — skip
        return None
    hist = terms = None
    for agg in plan.aggs:
        if not isinstance(agg, BucketAggExec) or agg.subs or agg.metrics:
            return None
        if agg.kind == "date_histogram" and hist is None:
            hist = agg
        elif agg.kind == "terms" and terms is None:
            terms = agg
        else:
            return None

    k = request.start_offset + request.max_hits
    if k > 0 and not plan.root.scoring:
        return None  # field-sorted hits: the comparator only models BM25

    def arr(slot):
        return np.ascontiguousarray(plan.arrays[slot])

    ids = arr(plan.root.ids_slot)
    tfs = arr(plan.root.tfs_slot)
    if plan.root.scoring:
        norms = arr(plan.root.norm_slot).astype(np.int32, copy=False)
        idf = float(np.asarray(plan.scalars[plan.root.idf_slot]))
        avg_len = float(np.asarray(plan.scalars[plan.root.avg_len_slot]))
    else:  # k == 0: the C++ loop never touches the scoring operands
        norms = np.zeros(1, np.int32)
        idf, avg_len = 0.0, 1.0

    if hist is not None:
        ts_values = arr(hist.values_slot).astype(np.int64, copy=False)
        ts_present = arr(hist.present_slot).astype(np.uint8, copy=False)
        origin = int(np.asarray(plan.scalars[hist.origin_slot]))
        interval = int(np.asarray(plan.scalars[hist.interval_slot]))
        n_hist = hist.num_buckets
    else:
        ts_values = np.zeros(1, np.int64)
        ts_present = np.zeros(1, np.uint8)
        origin, interval, n_hist = 0, 1, 0
    if terms is not None:
        ord_col = arr(terms.values_slot).astype(np.int32, copy=False)
        n_terms = terms.num_buckets
    else:
        ord_col = np.zeros(1, np.int32)
        n_terms = 0

    hist_out = np.zeros(max(n_hist, 1), np.int64)
    terms_out = np.zeros(max(n_terms, 1), np.int64)
    topk_scores = np.zeros(max(k, 1), np.float32)
    topk_docs = np.zeros(max(k, 1), np.int32)
    count_out = np.zeros(1, np.int64)

    def ptr(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    def run_once():
        hist_out[:] = 0
        terms_out[:] = 0
        lib.leaf_term_aggs(
            ptr(ids, ctypes.c_int32), ptr(tfs, ctypes.c_int32),
            ctypes.c_int64(len(ids)), ptr(norms, ctypes.c_int32),
            ctypes.c_int64(plan.num_docs),
            ptr(ts_values, ctypes.c_int64), ptr(ts_present, ctypes.c_uint8),
            ctypes.c_int64(origin), ctypes.c_int64(interval),
            ctypes.c_int32(n_hist),
            ptr(ord_col, ctypes.c_int32), ctypes.c_int32(n_terms),
            ctypes.c_double(idf), ctypes.c_double(avg_len),
            ctypes.c_int32(k),
            ptr(hist_out, ctypes.c_int64), ptr(terms_out, ctypes.c_int64),
            ptr(topk_scores, ctypes.c_float), ptr(topk_docs, ctypes.c_int32),
            ptr(count_out, ctypes.c_int64))

    run_once()
    if int(count_out[0]) != reference_count:
        print(f"# native comparator count mismatch: {int(count_out[0])} "
              f"vs {reference_count} — dropping denominator",
              file=sys.stderr)
        return None
    lat = []
    for _ in range(iters):
        t0 = time.monotonic()
        run_once()
        lat.append(time.monotonic() - t0)
    return {"native_cpu_ms": round(_percentile(lat, 0.5) * 1000, 3)}


def _native_cpu_bool_range(plan, request, reference_count: int,
                           iters: int) -> "dict | None":
    """Native comparator for the c2 shape (leafbench.cpp leaf_bool_range):
    one scored MUST term AND'ed with an integer range filter, up to two
    scored SHOULD terms on a shared field. Range bounds are fed in the
    column's own on-disk domain (raw values, or scaled deltas for
    FOR-packed columns), so the comparison is domain-invariant. Returns
    p50 ms or None when the plan is outside this shape."""
    import ctypes

    import numpy as np
    from quickwit_tpu.native import load_leafbench
    from quickwit_tpu.search.plan import PBool, PPostings, PRange

    lib = load_leafbench()
    k = request.start_offset + request.max_hits
    if lib is None or not isinstance(plan.root, PBool) or plan.aggs or k <= 0:
        return None
    node = plan.root
    if (len(node.must) != 1 or node.must_not or len(node.filter) != 1
            or len(node.should) > 2 or node.minimum_should_match):
        return None
    must, rng = node.must[0], node.filter[0]
    shoulds = list(node.should)
    if (not isinstance(must, PPostings) or not must.scoring
            or not isinstance(rng, PRange)):
        return None
    for s in shoulds:
        if not isinstance(s, PPostings) or not s.scoring:
            return None
    if len(shoulds) == 2 and shoulds[0].norm_slot != shoulds[1].norm_slot:
        return None  # the C++ models ONE shared should field
    for p in [must] + shoulds:
        if not plan.array_keys[p.ids_slot].startswith("post."):
            return None  # phrase/precomputed postings: out of scope

    def arr(slot, dt=None):
        a = np.ascontiguousarray(plan.arrays[slot])
        return a.astype(dt, copy=False) if dt is not None else a

    ts_values = arr(rng.values_slot)
    if ts_values.dtype.kind not in "iu" or ts_values.dtype == np.uint64:
        return None  # float ranges / full-width u64: not modeled
    ts_values = ts_values.astype(np.int64, copy=False)
    ts_present = arr(rng.present_slot, np.uint8)

    def bound(slot, default):
        return (int(np.asarray(plan.scalars[slot])) if slot >= 0
                else default)

    lo = bound(rng.lo_slot, -(2 ** 63))
    hi = bound(rng.hi_slot, 2 ** 63 - 1)
    if not rng.lo_incl:
        lo += 1
    if not rng.hi_incl:
        hi -= 1

    must_ids = arr(must.ids_slot)
    must_tfs = arr(must.tfs_slot)
    must_norms = arr(must.norm_slot, np.int32)
    must_idf = float(np.asarray(plan.scalars[must.idf_slot]))
    must_avg = float(np.asarray(plan.scalars[must.avg_len_slot]))
    empty = np.zeros(0, np.int32)
    s_arrs = [(arr(s.ids_slot), arr(s.tfs_slot)) for s in shoulds]
    while len(s_arrs) < 2:
        s_arrs.append((empty, empty))
    if shoulds:
        should_norms = arr(shoulds[0].norm_slot, np.int32)
        should_avg = float(np.asarray(plan.scalars[shoulds[0].avg_len_slot]))
    else:
        should_norms = np.zeros(1, np.int32)
        should_avg = 1.0
    s_idfs = [float(np.asarray(plan.scalars[s.idf_slot])) for s in shoulds]
    while len(s_idfs) < 2:
        s_idfs.append(0.0)

    topk_scores = np.zeros(max(k, 1), np.float32)
    topk_docs = np.zeros(max(k, 1), np.int32)
    count_out = np.zeros(1, np.int64)

    def ptr(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    def run_once():
        lib.leaf_bool_range(
            ptr(must_ids, ctypes.c_int32), ptr(must_tfs, ctypes.c_int32),
            ctypes.c_int64(len(must_ids)), ptr(must_norms, ctypes.c_int32),
            ctypes.c_double(must_idf), ctypes.c_double(must_avg),
            ptr(s_arrs[0][0], ctypes.c_int32),
            ptr(s_arrs[0][1], ctypes.c_int32),
            ctypes.c_int64(len(s_arrs[0][0])),
            ptr(s_arrs[1][0], ctypes.c_int32),
            ptr(s_arrs[1][1], ctypes.c_int32),
            ctypes.c_int64(len(s_arrs[1][0])),
            ptr(should_norms, ctypes.c_int32),
            ctypes.c_double(s_idfs[0]), ctypes.c_double(s_idfs[1]),
            ctypes.c_double(should_avg),
            ptr(ts_values, ctypes.c_int64), ptr(ts_present, ctypes.c_uint8),
            ctypes.c_int64(lo), ctypes.c_int64(hi),
            ctypes.c_int64(plan.num_docs), ctypes.c_int32(k),
            ptr(topk_scores, ctypes.c_float), ptr(topk_docs, ctypes.c_int32),
            ptr(count_out, ctypes.c_int64))

    run_once()
    if int(count_out[0]) != reference_count:
        print(f"# native bool+range comparator count mismatch: "
              f"{int(count_out[0])} vs {reference_count} — dropping "
              "denominator", file=sys.stderr)
        return None
    lat = []
    for _ in range(iters):
        t0 = time.monotonic()
        run_once()
        lat.append(time.monotonic() - t0)
    return {"native_cpu_ms": round(_percentile(lat, 0.5) * 1000, 3)}


def _batch_width_for(plan) -> int:
    """Queries per dispatch, bounded by per-lane device footprint: dense
    plans materialize [num_docs_padded] masks/scores/keys per lane, so a
    16-wide vmap over a 10M-doc dense plan would stack multi-GB
    intermediates; posting-space plans are far lighter."""
    from quickwit_tpu.search import executor as ex
    if ex._posting_space_eligible(plan):
        return PIPELINE_BATCH
    # dense per-lane intermediates ~ padded * ~48B (per-clause masks +
    # scores + f64 keys + sort scratch); keep the stack under ~4 GB
    per_lane = plan.num_docs_padded * 48
    width = max(1, min(PIPELINE_BATCH, (4 << 30) // max(per_lane, 1)))
    return 1 << (width.bit_length() - 1)  # power-of-two bucket


def _phase_breakdown(run_once) -> dict:
    """One profiled run of `run_once` → {phase: total_ms}: the same
    waterfall a `"profile": true` query returns, attached per config in
    BENCH_DETAILS.json so a regression can be blamed on a phase (staging
    vs compile vs execute) without re-running under a profiler."""
    from quickwit_tpu.observability.profile import (
        QueryProfile, profile_scope)
    profile = QueryProfile(query_id="bench")
    with profile_scope(profile):
        run_once()
    profile.finish()
    out: dict = {}
    for p in profile.phases():
        out[p["name"]] = round(out.get(p["name"], 0.0)
                               + p["duration_ms"], 3)
    return out


def _measure_batched_throughput(plan, k, device_arrays, num_queries: int,
                                batch: int) -> dict:
    """Per-query latency with `num_queries` concurrent queries executed as
    multi-query dispatches of width `batch` (the serving QueryBatcher's
    shape), dispatches pipelined. Returns the breakdown the round-3/4
    verdicts asked for: where each millisecond goes."""
    from quickwit_tpu.search import executor as ex
    nbatches = max(1, num_queries // batch)
    scalar_sets = [plan.scalars] * batch
    # warm: the vmapped program compiles once per (signature, batch)
    t0 = time.monotonic()
    ex.readback_plan_multi(
        ex.dispatch_plan_multi(plan, k, device_arrays, scalar_sets))
    warm_batch_s = time.monotonic() - t0

    # cache_scalars=False: every measured batch pays its scalar H2D upload,
    # as a mixed workload of DISTINCT concurrent queries would — the
    # content cache must not flatter the headline number
    t_all0 = time.monotonic()
    t0 = time.monotonic()
    dispatched = [ex.dispatch_plan_multi(plan, k, device_arrays, scalar_sets,
                                         cache_scalars=False)
                  for _ in range(nbatches)]
    dispatch_ms = (time.monotonic() - t0) * 1000
    t0 = time.monotonic()
    for d in dispatched:
        ex.readback_plan_multi(d)
    readback_ms = (time.monotonic() - t0) * 1000
    total = nbatches * batch
    return {
        "pipe_ms": round((time.monotonic() - t_all0) * 1000 / total, 2),
        "pipe_batch": batch,
        "pipe_breakdown": {
            "dispatch_host_ms": round(dispatch_ms / total, 3),
            "readback_wait_ms": round(readback_ms / total, 3),
            "warm_batch_s": round(warm_batch_s, 1),
        },
    }


def _measure_single_split(request, mapper, reader, iters: int,
                          full: bool = True) -> dict:
    """e2e / pipelined / device-time measurements for one-split configs."""
    import jax
    import jax.numpy as jnp
    from quickwit_tpu.search import executor as ex
    from quickwit_tpu.search.leaf import (
        leaf_search_single_split, prepare_single_split)

    t0 = time.monotonic()
    resp = leaf_search_single_split(request, mapper, reader, "bench")
    warm_s = time.monotonic() - t0
    stats = {"num_hits": int(resp.num_hits), "warm_s": round(warm_s, 1)}
    raw_est = (reader.footer.extra or {}).get("raw_json_bytes_est")
    if raw_est:
        # storage blowup of the TPU-padded split layout vs the ndjson a
        # user would have ingested (round-4 directive #5)
        stats["split_bytes"] = int(reader.file_len)
        stats["raw_json_bytes_est"] = int(raw_est)
        stats["split_vs_raw"] = round(reader.file_len / raw_est, 2)

    lat = []
    for _ in range(iters):
        t0 = time.monotonic()
        leaf_search_single_split(request, mapper, reader, "bench")
        lat.append(time.monotonic() - t0)
    stats["e2e_ms"] = round(_percentile(lat, 0.5) * 1000, 2)
    stats["e2e_p90_ms"] = round(_percentile(lat, 0.9) * 1000, 2)
    if full:
        stats["phases_ms"] = _phase_breakdown(
            lambda: leaf_search_single_split(request, mapper, reader,
                                             "bench"))

    plan, device_arrays, _ = prepare_single_split(
        request, mapper, reader, "bench")
    k = request.start_offset + request.max_hits
    width = _batch_width_for(plan)
    if not full:
        # CPU comparison child: e2e p50 + the SAME batched-throughput path
        # the TPU pipe number uses, so the pipelined ratio denominator is
        # the CPU's own best concurrent-query number, not its 1-shot one
        try:
            stats.update(_measure_batched_throughput(
                plan, k, device_arrays, PIPELINE_QUERIES, width))
        except Exception as exc:  # noqa: BLE001 - denominator must survive
            print(f"# cpu batched path failed ({exc}); e2e only",
                  file=sys.stderr)
        return stats

    stats["hbm_bytes"] = _estimate_bytes(plan)

    # native single-core C++ comparator on the same arrays (the honest
    # stand-in for the reference tantivy leaf; see _native_cpu_leaf)
    native = _native_cpu_leaf(plan, request, int(resp.num_hits),
                              max(5, iters // 2))
    if not native:
        # boolean AND/OR + range shape (c2): its own native kernel
        native = _native_cpu_bool_range(plan, request, int(resp.num_hits),
                                        max(5, iters // 2))
    if native:
        stats.update(native)

    # pipelined throughput: concurrent queries ride multi-query dispatches.
    # An untested-on-hardware failure (vmapped compile OOM etc.) must not
    # kill the bench: fall back to the solo-dispatch pipelined metric.
    try:
        stats.update(_measure_batched_throughput(
            plan, k, device_arrays, PIPELINE_QUERIES, width))
    except Exception as exc:  # noqa: BLE001 - record, fall back below
        print(f"# batched dispatch failed ({exc}); falling back to "
              "solo-dispatch pipelining", file=sys.stderr)

    # legacy one-query-per-dispatch pipelining, for the record: bounded by
    # the per-dispatch tunnel round (tools/profile_tunnel.py);
    # dispatch_plan itself starts the async D2H copy of the packed result
    inflight = []
    t0 = time.monotonic()
    for _ in range(PIPELINE_QUERIES):
        inflight.append(ex.dispatch_plan(plan, k, device_arrays))
        if len(inflight) > PIPELINE_DEPTH:
            ex.readback_plan_result(inflight.pop(0))
    while inflight:
        ex.readback_plan_result(inflight.pop(0))
    stats["pipe_solo_ms"] = round(
        (time.monotonic() - t0) * 1000 / PIPELINE_QUERIES, 2)
    if "pipe_ms" not in stats:  # batched path failed: solo is the metric
        stats["pipe_ms"] = stats["pipe_solo_ms"]
        stats["pipe_batch"] = 1

    # device time: fori_loop N-deep inside one dispatch, two depths
    single_fn = ex._build(plan, max(0, min(k, plan.num_docs_padded)))
    scalars, nd = ex._device_scalars(plan)
    arrays = tuple(device_arrays)

    def _repeat(n):
        def rep(arrays, scalars, num_docs):
            def body(i, acc):
                # the (i & 1) perturbation makes the body i-dependent so
                # XLA cannot hoist the loop-invariant kernel out
                out = single_fn(arrays, scalars, num_docs - (i & 1))
                for leaf in jax.tree_util.tree_leaves(out):
                    acc = acc + jnp.sum(leaf.astype(jnp.float32))
                return acc
            return jax.lax.fori_loop(0, n, body, jnp.float32(0))
        return jax.jit(rep)

    times = {}
    for depth in DEV_DEPTHS:
        fn = _repeat(depth)
        jax.block_until_ready(fn(arrays, scalars, nd))  # compile
        best = float("inf")
        for _ in range(3):
            t0 = time.monotonic()
            jax.block_until_ready(fn(arrays, scalars, nd))
            best = min(best, time.monotonic() - t0)
        times[depth] = best
    n1, n2 = DEV_DEPTHS
    dev_s = max((times[n2] - times[n1]) / (n2 - n1), 1e-9)
    stats["dev_ms"] = round(dev_s * 1000, 3)
    stats["hbm_gbps"] = round(stats["hbm_bytes"] / dev_s / 1e9, 1)
    return stats


def _measure_batch_otel(iters: int, full: bool = True) -> dict:
    """Config #5: duration percentiles across OTEL_SPLITS splits, executed
    as ONE vmapped XLA program on the chip (the multi-chip collective
    version of this shape is scored by c13_multichip)."""
    import jax
    import jax.numpy as jnp
    from quickwit_tpu.common.uri import Uri
    from quickwit_tpu.index.reader import SplitReader
    from quickwit_tpu.index.synthetic import (
        OTEL_BENCH_MAPPER, synthetic_otel_split)
    from quickwit_tpu.parallel import fanout
    from quickwit_tpu.query.ast import MatchAll
    from quickwit_tpu.search.models import SearchRequest
    from quickwit_tpu.storage.ram import RamStorage

    storage = RamStorage(Uri.parse("ram:///bench-otel"))
    readers = []
    for s in range(OTEL_SPLITS):
        storage.put(f"o{s}.split", synthetic_otel_split(OTEL_DOCS, seed=s))
        readers.append(SplitReader(storage, f"o{s}.split"))
    request = SearchRequest(
        index_ids=["otel-traces"], query_ast=MatchAll(), max_hits=0,
        aggs={"latency": {"percentiles": {"field": "span_duration_micros",
                                          "percents": [50, 95, 99]}}})
    batch = fanout.build_batch(request, OTEL_BENCH_MAPPER, readers,
                               [f"s{i}" for i in range(OTEL_SPLITS)])
    t0 = time.monotonic()
    resp = fanout.execute_batch(batch, request)
    warm_s = time.monotonic() - t0
    stats = {"num_hits": int(resp.num_hits), "warm_s": round(warm_s, 1),
             "n_splits": OTEL_SPLITS, "docs_per_split": OTEL_DOCS}

    lat = []
    for _ in range(iters):
        t0 = time.monotonic()
        fanout.execute_batch(batch, request)
        lat.append(time.monotonic() - t0)
    stats["e2e_ms"] = round(_percentile(lat, 0.5) * 1000, 2)
    if not full:
        return stats
    stats["phases_ms"] = _phase_breakdown(
        lambda: fanout.execute_batch(batch, request))

    # device time via the same two-depth fori_loop on the batch closure
    arrays, scalars, nd = fanout.stage_device_inputs(batch, None)
    fn_raw = fanout.batch_fn(batch, 0)

    def _repeat(n):
        def rep(arrays, scalars, num_docs):
            def body(i, acc):
                out = fn_raw(arrays, scalars, num_docs - (i & 1))
                for leaf in jax.tree_util.tree_leaves(out):
                    acc = acc + jnp.sum(leaf.astype(jnp.float32))
                return acc
            return jax.lax.fori_loop(0, n, body, jnp.float32(0))
        return jax.jit(rep)

    times = {}
    for depth in DEV_DEPTHS:
        fn = _repeat(depth)
        jax.block_until_ready(fn(arrays, scalars, nd))
        best = float("inf")
        for _ in range(3):
            t0 = time.monotonic()
            jax.block_until_ready(fn(arrays, scalars, nd))
            best = min(best, time.monotonic() - t0)
        times[depth] = best
    n1, n2 = DEV_DEPTHS
    dev_s = max((times[n2] - times[n1]) / (n2 - n1), 1e-9)
    stats["dev_ms"] = round(dev_s * 1000, 3)
    stats["hbm_bytes"] = sum(int(a.nbytes) for a in batch.arrays)
    stats["hbm_gbps"] = round(stats["hbm_bytes"] / dev_s / 1e9, 1)
    stats["splits_per_sec_dev"] = round(OTEL_SPLITS / dev_s)
    return stats


def _measure_pruning(iters: int) -> dict:
    """Config #6: dynamic top-K split pruning (search/pruning.py) over a
    time-partitioned index — N disjoint-window splits, term query sorted by
    timestamp desc. Measures the leaf latency with pruning on vs off (leaf
    cache disabled so every iteration really executes) and reports the new
    pruning counters: splits skipped by the threshold, splits downgraded to
    count-only when exact counts are required."""
    from quickwit_tpu.index.synthetic import HDFS_MAPPER, synthetic_hdfs_split
    from quickwit_tpu.query.ast import Term
    from quickwit_tpu.search.models import (
        LeafSearchRequest, SearchRequest, SortField, SplitIdAndFooter)
    from quickwit_tpu.search.service import SearcherContext, SearchService
    from quickwit_tpu.storage import StorageResolver

    n_splits = int(os.environ.get("BENCH_PRUNE_SPLITS", 16))
    docs_per = int(os.environ.get("BENCH_PRUNE_DOCS", 65_536))
    resolver = StorageResolver.for_test()
    storage = resolver.resolve("ram:///bench-prune")
    day = 86_400
    offsets = []
    for s in range(n_splits):
        start = 1_600_000_000 + s * day
        storage.put(f"p{s}.split", synthetic_hdfs_split(
            docs_per, seed=100 + s, start_ts=start, span_seconds=day))
        offsets.append(SplitIdAndFooter(
            split_id=f"p{s}", storage_uri="ram:///bench-prune",
            num_docs=docs_per,
            time_range=(start * 1_000_000, (start + day) * 1_000_000)))

    def run(pruning, exact):
        service = SearchService(SearcherContext(
            storage_resolver=resolver, batch_size=1, prefetch=False,
            leaf_cache_bytes=0, enable_threshold_pruning=pruning))
        request = LeafSearchRequest(
            search_request=SearchRequest(
                index_ids=["hdfs-logs"],
                query_ast=Term("severity_text", "ERROR"), max_hits=10,
                sort_fields=(SortField("timestamp", "desc"),),
                count_hits_exact=exact),
            index_uid="bench:prune", doc_mapping=HDFS_MAPPER.to_dict(),
            splits=offsets)
        service.leaf_search(request)  # warm readers + compile
        lat = []
        response = None
        for _ in range(iters):
            t0 = time.monotonic()
            response = service.leaf_search(request)
            lat.append(time.monotonic() - t0)
        return response, _percentile(lat, 0.5) * 1000, \
            lambda: service.leaf_search(request)

    resp_on, on_ms, rerun_on = run(pruning=True, exact=False)
    resp_off, off_ms, _ = run(pruning=False, exact=False)
    resp_count, count_ms, _ = run(pruning=True, exact=True)
    return {
        "n_splits": n_splits, "docs_per_split": docs_per,
        "phases_ms": _phase_breakdown(rerun_on),
        "e2e_ms": round(on_ms, 2),           # pruned leaf, the real path
        "unpruned_ms": round(off_ms, 2),
        "pruning_speedup": round(off_ms / max(on_ms, 1e-9), 2),
        "splits_pruned_by_threshold": int(
            resp_on.resource_stats.get("num_splits_pruned_by_threshold", 0)),
        "exact_count_ms": round(count_ms, 2),
        "splits_downgraded_to_count": int(
            resp_count.resource_stats.get(
                "num_splits_downgraded_to_count", 0)),
    }


def _measure_tenant_isolation(duration_secs: float = 1.0) -> dict:
    """Config #7: noisy-neighbor isolation on the HBM admission queue
    (tenancy/drr.py via search/admission.py). A background-class tenant
    floods the single admission slot from several threads while an
    interactive-class victim runs a steady trickle; reports the victim's
    p99 admission wait alone vs under the storm and their ratio — the
    number the weighted deficit-round-robin scheduler exists to bound."""
    import threading

    from quickwit_tpu.search.admission import HbmBudget
    from quickwit_tpu.tenancy.context import TenantContext, tenant_scope

    cost = 1_000
    hold_secs = 0.002
    n_victim = int(os.environ.get("BENCH_TENANT_QUERIES", 50))

    def run_victim(budget, n):
        tenant = TenantContext.for_class("victim", "interactive")
        owner = object()
        waits = []
        for _ in range(n):
            with tenant_scope(tenant):
                t0 = time.monotonic()
                budget.admit(owner, cost, timeout_secs=30.0)
            waits.append(time.monotonic() - t0)
            time.sleep(hold_secs)
            budget.release(owner, cost, to_resident=False)
        return waits

    alone = run_victim(HbmBudget(budget_bytes=cost), n_victim)

    budget = HbmBudget(budget_bytes=cost)
    stop = threading.Event()
    flood_admissions = [0]

    def flood():
        tenant = TenantContext.for_class("flood", "background")
        owner = object()
        while not stop.is_set():
            with tenant_scope(tenant):
                try:
                    budget.admit(owner, cost, timeout_secs=5.0)
                except TimeoutError:
                    continue
            flood_admissions[0] += 1
            time.sleep(hold_secs)
            budget.release(owner, cost, to_resident=False)

    flooders = [threading.Thread(target=flood, daemon=True)
                for _ in range(6)]
    for thread in flooders:
        thread.start()
    try:
        stormed = run_victim(budget, n_victim)
    finally:
        stop.set()
        for thread in flooders:
            thread.join(timeout=10)

    p99_alone = _percentile(alone, 0.99)
    p99_storm = _percentile(stormed, 0.99)
    return {
        "victim_queries": n_victim,
        "flood_threads": 6,
        "flood_admissions": flood_admissions[0],
        "p99_alone_ms": round(p99_alone * 1000, 3),
        "p99_storm_ms": round(p99_storm * 1000, 3),
        # the headline: bounded noisy-neighbor degradation (lower = better)
        "noisy_neighbor_p99_ratio": round(
            p99_storm / max(p99_alone, 1e-4), 2),
        "mean_storm_ms": round(
            sum(stormed) / len(stormed) * 1000, 3),
    }


def _measure_offload_scaling() -> dict:
    """Config #8: elastic offload pool scaling (quickwit_tpu/offload/).
    A storm of concurrent leaf dispatches fans the same cold-split tail
    over 1/2/4 in-process workers (real SearchService leaves over shared
    ram:// storage, rendezvous placement + hedging/stealing live);
    reports per-pool-size dispatch p50/p99 and the 1→4-worker p99
    speedup the elastic pool exists to buy under concurrency."""
    import threading

    from quickwit_tpu.common.deadline import Deadline
    from quickwit_tpu.indexing import (
        IndexingPipeline, PipelineParams, VecSource,
    )
    from quickwit_tpu.metastore import FileBackedMetastore
    from quickwit_tpu.metastore.base import ListSplitsQuery
    from quickwit_tpu.models import DocMapper, FieldMapping, FieldType
    from quickwit_tpu.models.index_metadata import (
        IndexConfig, IndexMetadata, SourceConfig,
    )
    from quickwit_tpu.offload import OffloadDispatcher, WorkerPool
    from quickwit_tpu.query import parse_query_string
    from quickwit_tpu.search.models import (
        LeafSearchRequest, SearchRequest, SplitIdAndFooter,
    )
    from quickwit_tpu.search.service import (
        LocalSearchClient, SearcherContext, SearchService,
    )
    from quickwit_tpu.storage import StorageResolver

    num_splits = 8
    docs_per_split = 100
    storm_threads = int(os.environ.get("BENCH_OFFLOAD_THREADS", 4))
    queries_per_thread = int(os.environ.get("BENCH_OFFLOAD_QUERIES", 6))

    mapper = DocMapper(field_mappings=[FieldMapping("body", FieldType.TEXT)],
                       default_search_fields=("body",))
    resolver = StorageResolver.for_test()
    metastore = FileBackedMetastore(resolver.resolve("ram:///bench-ol/ms"))
    split_uri = "ram:///bench-ol/splits"
    metastore.create_index(IndexMetadata(
        index_uid="bench-ol:01",
        index_config=IndexConfig(index_id="bench-ol", index_uri=split_uri,
                                 doc_mapper=mapper,
                                 split_num_docs_target=docs_per_split),
        sources={"src": SourceConfig("src", "vec")}))
    docs = [{"body": f"event {i} common"}
            for i in range(num_splits * docs_per_split)]
    IndexingPipeline(
        PipelineParams(index_uid="bench-ol:01", source_id="src",
                       split_num_docs_target=docs_per_split,
                       batch_num_docs=docs_per_split),
        mapper, VecSource(docs), metastore,
        resolver.resolve(split_uri)).run_to_completion()
    splits = [SplitIdAndFooter(split_id=s.metadata.split_id,
                               storage_uri=split_uri,
                               num_docs=s.metadata.num_docs)
              for s in metastore.list_splits(ListSplitsQuery())]
    request = LeafSearchRequest(
        search_request=SearchRequest(
            index_ids=["bench-ol"],
            query_ast=parse_query_string("body:common"), max_hits=10),
        index_uid="bench-ol:01", doc_mapping=mapper.to_dict(),
        splits=splits)

    def storm(num_workers: int) -> dict:
        pool = WorkerPool()
        for i in range(num_workers):
            worker_id = f"bw-{i}"
            pool.add_worker(worker_id, LocalSearchClient(SearchService(
                SearcherContext(resolver, prefetch=False),
                node_id=worker_id)))
        dispatcher = OffloadDispatcher(pool, task_splits=2)
        # one warmup dispatch opens every worker's readers off the clock
        dispatcher.dispatch(request, deadline=Deadline.after(60.0))
        latencies: list = []
        lock = threading.Lock()

        def client():
            for _ in range(queries_per_thread):
                t0 = time.monotonic()
                outcome = dispatcher.dispatch(request,
                                              deadline=Deadline.after(60.0))
                elapsed = time.monotonic() - t0
                assert not outcome.unserved
                with lock:
                    latencies.append(elapsed)

        threads = [threading.Thread(target=client)
                   for _ in range(storm_threads)]
        t0 = time.monotonic()
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        wall = time.monotonic() - t0
        return {
            "p50_ms": round(_percentile(latencies, 0.50) * 1000, 2),
            "p99_ms": round(_percentile(latencies, 0.99) * 1000, 2),
            "dispatches_per_s": round(len(latencies) / wall, 1),
        }

    by_pool_size = {f"{n}_workers": storm(n) for n in (1, 2, 4)}
    return {
        "storm_threads": storm_threads,
        "queries_per_thread": queries_per_thread,
        "num_splits": num_splits,
        "pool": by_pool_size,
        # the headline: concurrent-dispatch tail latency bought per worker
        "p99_speedup_1w_to_4w": round(
            by_pool_size["1_workers"]["p99_ms"]
            / max(by_pool_size["4_workers"]["p99_ms"], 1e-3), 2),
    }


def _measure_resident_warm(iters: int) -> dict:
    """Config #9: the resident-column serving path (search/residency.py).

    N splits through a one-slot reader LRU, so every query reopens its
    readers — the worst case for the seed's per-reader device cache, which
    died with the reader and re-paid full H2D staging per query. With the
    resident store the columns survive reader churn keyed by split id:
    warm queries stage ZERO column bytes (counter-verified per query).
    Leaf response cache off and threshold pruning off so every iteration
    executes and warms every split."""
    from quickwit_tpu.index.synthetic import HDFS_MAPPER, synthetic_hdfs_split
    from quickwit_tpu.query.ast import Term
    from quickwit_tpu.search.models import (
        LeafSearchRequest, SearchRequest, SortField, SplitIdAndFooter)
    from quickwit_tpu.search.residency import (
        RESIDENT_COLUMN_MISSES, RESIDENT_STAGING_CACHE_HITS)
    from quickwit_tpu.search.service import SearcherContext, SearchService
    from quickwit_tpu.storage import StorageResolver

    n_splits = int(os.environ.get("BENCH_RESIDENT_SPLITS", 8))
    docs_per = int(os.environ.get("BENCH_RESIDENT_DOCS", 65_536))
    resolver = StorageResolver.for_test()
    storage = resolver.resolve("ram:///bench-resident")
    day = 86_400
    offsets = []
    for s in range(n_splits):
        start = 1_600_000_000 + s * day
        storage.put(f"r{s}.split", synthetic_hdfs_split(
            docs_per, seed=200 + s, start_ts=start, span_seconds=day))
        offsets.append(SplitIdAndFooter(
            split_id=f"r{s}", storage_uri="ram:///bench-resident",
            num_docs=docs_per,
            time_range=(start * 1_000_000, (start + day) * 1_000_000)))

    request = LeafSearchRequest(
        search_request=SearchRequest(
            index_ids=["hdfs-logs"],
            query_ast=Term("severity_text", "ERROR"), max_hits=10,
            sort_fields=(SortField("timestamp", "desc"),)),
        index_uid="bench:resident", doc_mapping=HDFS_MAPPER.to_dict(),
        splits=offsets)

    def run(resident):
        service = SearchService(SearcherContext(
            storage_resolver=resolver, batch_size=1, prefetch=False,
            leaf_cache_bytes=0, enable_threshold_pruning=False,
            max_open_splits=1, resident_columns=resident))
        t0 = time.monotonic()
        service.leaf_search(request)  # cold: compile + stage every split
        cold_s = time.monotonic() - t0
        # counter deltas over the WARM loop only: hits must be
        # iters * n_splits, uploads must be zero
        hits0 = RESIDENT_STAGING_CACHE_HITS.get()
        misses0 = RESIDENT_COLUMN_MISSES.get()
        lat = []
        for _ in range(iters):
            t0 = time.monotonic()
            response = service.leaf_search(request)
            lat.append(time.monotonic() - t0)
        assert not response.failed_splits
        return {
            "cold_s": round(cold_s, 1),
            "warm_ms": _percentile(lat, 0.5) * 1000,
            "hits": RESIDENT_STAGING_CACHE_HITS.get() - hits0,
            "uploads": RESIDENT_COLUMN_MISSES.get() - misses0,
            "rerun": lambda: service.leaf_search(request),
        }

    res = run(resident=True)
    churn = run(resident=False)  # store off: no counters touched
    return {
        "n_splits": n_splits, "docs_per_split": docs_per,
        "cold_s": res["cold_s"],
        "e2e_ms": round(res["warm_ms"], 2),   # warm resident, the real path
        "reader_churn_ms": round(churn["warm_ms"], 2),  # seed: residency
                                       # died with the reader, re-staged all
        "resident_warm_speedup": round(
            churn["warm_ms"] / max(res["warm_ms"], 1e-9), 2),
        "staging_cache_hits": int(res["hits"]),  # iters * n_splits expected
        "warm_column_uploads": int(res["uploads"]),  # must be 0
        "phases_ms": _phase_breakdown(res["rerun"]),
    }


def _measure_impact_ordered(iters: int) -> dict:
    """Config #10: impact-ordered postings + block-max prefix cutoff
    (index/impact.py, format v3).

    The same synthetic splits built twice — impact-ordered and, via the
    QW_DISABLE_IMPACT kill switch, doc-ordered v2 layout — and queried
    with a score-sorted single term whose threshold (the collector's Kth
    value) is pushed into the leaf. On the v3 corpus the lowering cuts the
    staged postings to the live impact prefix and the kernel masks whole
    blocks below the pushed bound; the counters prove blocks were skipped
    and staging bytes avoided, and the hit lists are asserted identical
    across both layouts (the whole point: skipping is invisible).
    Leaf cache off so every iteration actually executes."""
    from quickwit_tpu.index.synthetic import (
        HDFS_MAPPER, body_term, synthetic_hdfs_split)
    from quickwit_tpu.observability.metrics import (
        IMPACT_BLOCKS_SCORED_TOTAL, IMPACT_BLOCKS_SKIPPED_TOTAL,
        IMPACT_POSTINGS_BYTES_AVOIDED_TOTAL, IMPACT_PREFIX_CUTOFFS_TOTAL)
    from quickwit_tpu.query.ast import Term
    from quickwit_tpu.search.models import (
        LeafSearchRequest, SearchRequest, SortField, SplitIdAndFooter)
    from quickwit_tpu.search.service import SearcherContext, SearchService
    from quickwit_tpu.storage import StorageResolver

    n_splits = int(os.environ.get("BENCH_IMPACT_SPLITS", 4))
    docs_per = int(os.environ.get("BENCH_IMPACT_DOCS", 65_536))
    resolver = StorageResolver.for_test()

    def build(uri, disable_impact):
        storage = resolver.resolve(uri)
        if disable_impact:
            os.environ["QW_DISABLE_IMPACT"] = "1"
        try:
            offsets = []
            for s in range(n_splits):
                storage.put(f"i{s}.split", synthetic_hdfs_split(
                    docs_per, seed=300 + s))
                offsets.append(SplitIdAndFooter(
                    split_id=f"i{s}", storage_uri=uri, num_docs=docs_per,
                    time_range=None))
            return offsets
        finally:
            os.environ.pop("QW_DISABLE_IMPACT", None)
    v3 = build("ram:///bench-impact-v3", disable_impact=False)
    v2 = build("ram:///bench-impact-v2", disable_impact=True)

    def leaf_request(offsets, threshold):
        return LeafSearchRequest(
            search_request=SearchRequest(
                index_ids=["hdfs-logs"],
                query_ast=Term("body", body_term(3)), max_hits=10,
                sort_fields=(SortField("_score", "desc"),)),
            index_uid="bench:impact", doc_mapping=HDFS_MAPPER.to_dict(),
            splits=offsets, sort_value_threshold=threshold)

    def fresh_service():
        # the leaf cache key ignores the threshold, so measured calls need
        # either a fresh service or (for the warm loops) the cache off
        return SearchService(SearcherContext(
            storage_resolver=resolver, batch_size=1, prefetch=False,
            leaf_cache_bytes=0))

    base = fresh_service().leaf_search(leaf_request(v3, None))
    threshold = base.partial_hits[-1].sort_value
    c0 = (IMPACT_BLOCKS_SCORED_TOTAL.get(), IMPACT_BLOCKS_SKIPPED_TOTAL.get(),
          IMPACT_POSTINGS_BYTES_AVOIDED_TOTAL.get(),
          IMPACT_PREFIX_CUTOFFS_TOTAL.get())
    pushed = fresh_service().leaf_search(leaf_request(v3, threshold))
    scored, skipped, avoided, cutoffs = (
        IMPACT_BLOCKS_SCORED_TOTAL.get() - c0[0],
        IMPACT_BLOCKS_SKIPPED_TOTAL.get() - c0[1],
        IMPACT_POSTINGS_BYTES_AVOIDED_TOTAL.get() - c0[2],
        IMPACT_PREFIX_CUTOFFS_TOTAL.get() - c0[3])
    v2_pushed = fresh_service().leaf_search(leaf_request(v2, threshold))

    def keys(resp):
        return [(h.split_id, h.doc_id, h.sort_value)
                for h in resp.partial_hits]
    assert keys(pushed) == keys(base) == keys(v2_pushed), \
        "impact-ordered results diverged from the doc-ordered baseline"
    assert skipped > 0 and avoided > 0, \
        "threshold pushed but no impact blocks were skipped"

    def warm(offsets, thr):
        service = fresh_service()
        request = leaf_request(offsets, thr)
        service.leaf_search(request)  # cold: compile + first staging
        lat = []
        for _ in range(iters):
            t0 = time.monotonic()
            service.leaf_search(request)
            lat.append(time.monotonic() - t0)
        return _percentile(lat, 0.5) * 1000
    v3_ms = warm(v3, threshold)
    v2_ms = warm(v2, threshold)
    nothr_ms = warm(v3, None)
    return {
        "n_splits": n_splits, "docs_per_split": docs_per,
        "e2e_ms": round(v3_ms, 2),            # v3, threshold pushed
        "doc_ordered_ms": round(v2_ms, 2),    # v2 twin, same threshold
        "no_threshold_ms": round(nothr_ms, 2),
        "impact_speedup": round(v2_ms / max(v3_ms, 1e-9), 2),
        "prefix_cutoffs": int(cutoffs),       # per thresholded cold query
        "blocks_scored": int(scored),
        "blocks_skipped": int(skipped),
        "staged_bytes_avoided": int(avoided),
        "skip_ratio": round(skipped / max(scored + skipped, 1), 3),
    }


def _measure_dashboard_qps(iters: int) -> dict:
    """Config #11: the hierarchical-cache dashboard workload
    (docs/hierarchical-cache.md). N panels share ONE filter but carry
    distinct agg shapes — the shape a dashboard refresh fans out as. With
    the mask + partial-agg tiers on, warm count/agg panels short-circuit
    to cached partials (zero kernel launches) and warm hit panels reuse
    the cached predicate mask (zero predicate-column bytes staged); the
    cache-disabled twin re-evaluates the same filter per panel. Reports
    concurrent QPS, p50/p99, and the staged-bytes / kernel-launches
    avoided. Leaf cache off so the tiers (not whole-response reuse) are
    what is measured; both counter claims are asserted, and every panel's
    response is asserted identical across the twins."""
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from quickwit_tpu.index.synthetic import (
        HDFS_MAPPER, body_term, synthetic_hdfs_split)
    from quickwit_tpu.observability.metrics import (
        PREDICATE_STAGED_BYTES_TOTAL, SEARCH_KERNEL_LAUNCHES_TOTAL,
        STAGING_BYTES_TOTAL)
    from quickwit_tpu.query.ast import Term
    from quickwit_tpu.search.models import (
        LeafSearchRequest, SearchRequest, SortField, SplitIdAndFooter)
    from quickwit_tpu.search.service import SearcherContext, SearchService
    from quickwit_tpu.storage import StorageResolver

    n_splits = int(os.environ.get("BENCH_DASH_SPLITS", 4))
    docs_per = int(os.environ.get("BENCH_DASH_DOCS", 65_536))
    concurrency = int(os.environ.get("BENCH_DASH_CONCURRENCY", 4))
    resolver = StorageResolver.for_test()
    storage = resolver.resolve("ram:///bench-dash")
    offsets = []
    for s in range(n_splits):
        storage.put(f"d{s}.split",
                    synthetic_hdfs_split(docs_per, seed=500 + s))
        offsets.append(SplitIdAndFooter(
            split_id=f"d{s}", storage_uri="ram:///bench-dash",
            num_docs=docs_per))

    shapes = {
        "sev": {"terms": {"field": "severity_text"}},
        "tenants": {"terms": {"field": "tenant_id"}},
        "tenant_stats": {"stats": {"field": "tenant_id"}},
        "per_hour": {"date_histogram": {"field": "timestamp",
                                        "fixed_interval": "1h"}},
        "per_30m": {"date_histogram": {"field": "timestamp",
                                       "fixed_interval": "30m"}},
        "per_2h": {"date_histogram": {"field": "timestamp",
                                      "fixed_interval": "2h"}},
    }
    shared_filter = Term("body", body_term(3))

    def panel(name, spec, max_hits):
        return LeafSearchRequest(
            search_request=SearchRequest(
                index_ids=["hdfs-logs"], query_ast=shared_filter,
                max_hits=max_hits, aggs={name: spec},
                sort_fields=(SortField("timestamp", "desc"),)),
            index_uid="bench:dash", doc_mapping=HDFS_MAPPER.to_dict(),
            splits=offsets)

    # half the dashboard is count/agg-only (Tier B short-circuit), half
    # carries a top-hits page (Tier A mask path)
    panels = [panel(name, spec, 0 if i % 2 == 0 else 10)
              for i, (name, spec) in enumerate(shapes.items())]

    def make_service(enabled):
        return SearchService(SearcherContext(
            storage_resolver=resolver, batch_size=1, prefetch=False,
            leaf_cache_bytes=0, enable_mask_cache=enabled,
            enable_agg_cache=enabled))

    counter_lock = threading.Lock()

    def drive(service):
        cold = [service.leaf_search(p) for p in panels]  # compile + fill
        for p in panels:
            service.leaf_search(p)  # warm plans (mask-hit shape compiles)
        staged0 = STAGING_BYTES_TOTAL.get()
        pred0 = PREDICATE_STAGED_BYTES_TOTAL.get()
        launches0 = SEARCH_KERNEL_LAUNCHES_TOTAL.get()
        lat = []

        def one(p):
            t0 = time.monotonic()
            service.leaf_search(p)
            dt = time.monotonic() - t0
            with counter_lock:
                lat.append(dt)

        t_start = time.monotonic()
        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            for _ in range(iters):
                list(pool.map(one, panels))
        wall = time.monotonic() - t_start
        return cold, {
            "qps": round(len(lat) / max(wall, 1e-9), 1),
            "p50_ms": round(_percentile(lat, 0.5) * 1000, 2),
            "p99_ms": round(_percentile(lat, 0.99) * 1000, 2),
            "staged_bytes": int(STAGING_BYTES_TOTAL.get() - staged0),
            "predicate_staged_bytes": int(
                PREDICATE_STAGED_BYTES_TOTAL.get() - pred0),
            "kernel_launches": int(
                SEARCH_KERNEL_LAUNCHES_TOTAL.get() - launches0),
            # device dispatches per panel query served: <1.0 means
            # short-circuits (agg tier) and/or multi-query stacking are
            # amortizing launches across the dashboard
            "launches_per_query": round(
                (SEARCH_KERNEL_LAUNCHES_TOTAL.get() - launches0)
                / max(len(lat), 1), 3),
        }

    cached_cold, hot = drive(make_service(True))
    twin_cold, cold = drive(make_service(False))

    # staging attribution under node churn: in-process, the resident
    # column store (PR 9) already absorbs repeat staging, so the mask
    # tier's staged-bytes win shows on a FRESH context (restart / leaf
    # churn) whose cache tier survived — it stages sort/agg columns plus a
    # 128-byte mask, never the postings the filter was built from
    def churn(enabled, rounds, warm_tier=None):
        staged0 = STAGING_BYTES_TOTAL.get()
        pred0 = PREDICATE_STAGED_BYTES_TOTAL.get()
        for _ in range(rounds):
            service = make_service(enabled)
            if warm_tier is not None:
                service.context.mask_cache = warm_tier[0]
                service.context.agg_cache = warm_tier[1]
            service.leaf_search(panels[1])  # a top-hits (mask-path) panel
        return (int(STAGING_BYTES_TOTAL.get() - staged0),
                int(PREDICATE_STAGED_BYTES_TOTAL.get() - pred0))

    seed_service = make_service(True)
    seed_service.leaf_search(panels[1])  # fill the tier once
    warm_tier = (seed_service.context.mask_cache,
                 seed_service.context.agg_cache)
    churn_rounds = 3
    cached_staged, cached_pred = churn(True, churn_rounds, warm_tier)
    twin_staged, twin_pred = churn(False, churn_rounds)
    assert cached_pred == 0, \
        "mask-hit panels on fresh nodes staged predicate columns"
    assert twin_pred > 0, \
        "cache-disabled twin staged no predicate columns (probe broken)"

    for a, b in zip(cached_cold, twin_cold):
        assert a.num_hits == b.num_hits and json.dumps(
            a.intermediate_aggs, sort_keys=True, default=repr) == json.dumps(
            b.intermediate_aggs, sort_keys=True, default=repr), \
            "hierarchical caches changed a dashboard panel's results"
    # the tentpole's acceptance claim: a warm dashboard stages ZERO
    # predicate-column bytes (mask hits) while the cache-disabled twin
    # re-stages the filter columns it just threw away
    assert hot["predicate_staged_bytes"] == 0, \
        "warm mask-path panels staged predicate columns"
    assert hot["kernel_launches"] < cold["kernel_launches"], \
        "Tier B short-circuit launched as many kernels as the twin"

    return {
        "n_panels": len(panels), "n_splits": n_splits,
        "docs_per_split": docs_per, "concurrency": concurrency,
        "e2e_ms": hot["p50_ms"],  # headline: warm cached panel p50
        "cached": hot, "uncached": cold,
        "qps_speedup": round(hot["qps"] / max(cold["qps"], 1e-9), 2),
        "p99_speedup": round(cold["p99_ms"] / max(hot["p99_ms"], 1e-9), 2),
        "kernel_launches_avoided":
            cold["kernel_launches"] - hot["kernel_launches"],
        # per fresh-node query on a tier-warm filter (churn phase)
        "staged_bytes_avoided": (twin_staged - cached_staged) // churn_rounds,
        "predicate_staged_bytes_avoided":
            (twin_pred - cached_pred) // churn_rounds,
    }


def _measure_preemption() -> dict:
    """Config #12: mid-query tenant preemption at chunk boundaries
    (search/chunkexec.py). A background-class tenant scans a big split
    in a loop while the overload ladder is tripped; interactive-class
    arrivals declare themselves through the preempt gate. With the
    resumable chunked scan the background query parks its carried state
    at the NEXT chunk boundary; fused, the earliest it can yield is the
    end of the whole split. Reports the interactive-visible reaction
    latency p50/p99 under both, the fused→chunked p99 improvement, and
    the warm single-query overhead of the chunked scan vs the fused
    kernel on the same split (the ≤5% budget the adaptive sizer holds)."""
    import threading

    import numpy as np

    from quickwit_tpu.index import SplitReader
    from quickwit_tpu.index.synthetic import HDFS_MAPPER, synthetic_hdfs_split
    from quickwit_tpu.query.ast import Term
    from quickwit_tpu.search import chunkexec, executor
    from quickwit_tpu.search.chunkexec import CHUNKING, PREEMPT_GATE
    from quickwit_tpu.search.plan import lower_request
    from quickwit_tpu.storage import StorageResolver
    from quickwit_tpu.tenancy.overload import OVERLOAD

    docs = int(os.environ.get("BENCH_PREEMPT_DOCS", 524_288))
    n_interactive = int(os.environ.get("BENCH_PREEMPT_QUERIES", 25))
    k = 10
    resolver = StorageResolver.for_test()
    storage = resolver.resolve("ram:///bench-preempt")
    storage.put("big.split", synthetic_hdfs_split(docs, seed=900))
    storage.put("small.split", synthetic_hdfs_split(4096, seed=901))
    big = SplitReader(storage, "big.split")
    small = SplitReader(storage, "small.split")
    # the background tenant's analytics scan: dense full-split sweep with
    # a date histogram — the hundreds-of-ms query class preemption exists
    # to get out of an interactive arrival's way
    from quickwit_tpu.query.aggregations import DateHistogramAgg, MetricAgg
    from quickwit_tpu.query.ast import MatchAll
    aggs = [DateHistogramAgg(
        name="per_hour", field="timestamp", interval_micros=3_600 * 10**6,
        sub_metrics=(MetricAgg("tid_avg", "avg", "tenant_id"),))]
    plan = lower_request(MatchAll(), HDFS_MAPPER, big, aggs,
                         sort_field="timestamp", sort_order="desc")
    arrays = list(plan.arrays)
    small_plan = lower_request(Term("severity_text", "ERROR"), HDFS_MAPPER,
                               small, [])
    small_arrays = list(small_plan.arrays)
    mode, total, align = chunkexec.chunk_mode(plan)
    # pinned 8-slab span: the sizer must not collapse the scan mid-bench
    span = max(align, (total // 8 // align) * align)
    n_chunks = len(chunkexec.chunk_spans(total, span, align))
    assert n_chunks >= 4, "bench split too small to chunk meaningfully"

    # warm both paths (compiles) and assert the chunked scan is exact
    fused = executor.execute_plan(plan, k, arrays)
    chunked = chunkexec.execute_plan_chunked(plan, k, arrays, span=span)
    assert chunked is not None
    np.testing.assert_array_equal(np.asarray(fused["doc_ids"]),
                                  np.asarray(chunked["doc_ids"]))
    executor.execute_plan(small_plan, k, small_arrays)

    def p50_secs(fn, n=7):
        lat = []
        for _ in range(n):
            t0 = time.monotonic()
            fn()
            lat.append(time.monotonic() - t0)
        return _percentile(lat, 0.5)

    fused_scan_ms = p50_secs(
        lambda: executor.execute_plan(plan, k, arrays)) * 1000
    chunked_scan_ms = p50_secs(
        lambda: chunkexec.execute_plan_chunked(plan, k, arrays,
                                               span=span)) * 1000

    from quickwit_tpu.tenancy.context import TenantContext, tenant_scope
    bg_tenant = TenantContext.for_class("bench-bg", "background")

    def reaction_run(enabled):
        was_enabled = CHUNKING.enabled
        CHUNKING.set(enabled=enabled)
        OVERLOAD.configure(enabled=True, target_wait_secs=0.01)
        for _ in range(20):
            OVERLOAD.note_wait(1.0)  # trip the shed floor: ladder active
        assert OVERLOAD.shed_floor() > 0
        stop = threading.Event()
        gate_ack = threading.Event()

        def background():
            with tenant_scope(bg_tenant):
                while not stop.is_set():
                    if PREEMPT_GATE.should_yield(0):
                        # fused path's earliest yield point: between scans
                        gate_ack.set()
                        PREEMPT_GATE.wait_until_clear(0, 2.0)
                        continue
                    if enabled:
                        # parks INSIDE at the next boundary when an
                        # interactive query is running (PREEMPT_TOTAL)
                        chunkexec.execute_plan_chunked(plan, k, arrays,
                                                       span=span)
                    else:
                        executor.execute_plan(plan, k, arrays)

        thread = threading.Thread(target=background, daemon=True)
        thread.start()
        reactions = []
        try:
            time.sleep(0.05)  # let the background scan get mid-flight
            for _ in range(n_interactive):
                before = chunkexec.PREEMPT_TOTAL.get()
                gate_ack.clear()
                t0 = time.monotonic()
                with PREEMPT_GATE.running(2):
                    while (not gate_ack.is_set()
                           and chunkexec.PREEMPT_TOTAL.get() <= before
                           and time.monotonic() - t0 < 10.0):
                        time.sleep(0.0002)
                    reactions.append(time.monotonic() - t0)
                    # the interactive query itself, while holding the slot
                    executor.execute_plan(small_plan, k, small_arrays)
                time.sleep(0.01)  # background resumes and gets mid-scan
        finally:
            stop.set()
            thread.join(timeout=10.0)
            OVERLOAD.reset()
            OVERLOAD.configure(enabled=False, target_wait_secs=0.5)
            CHUNKING.set(enabled=was_enabled)
        return {
            "p50_ms": round(_percentile(reactions, 0.5) * 1000, 3),
            "p99_ms": round(_percentile(reactions, 0.99) * 1000, 3),
        }

    preempts0 = chunkexec.PREEMPT_TOTAL.get()
    chunked_reaction = reaction_run(enabled=True)
    preempts = int(chunkexec.PREEMPT_TOTAL.get() - preempts0)
    fused_reaction = reaction_run(enabled=False)
    return {
        "docs": docs, "n_chunks": n_chunks,
        "interactive_queries": n_interactive,
        "preempts": preempts,
        "chunked_reaction": chunked_reaction,
        "fused_reaction": fused_reaction,
        # the headline: interactive arrivals see the accelerator within
        # one chunk boundary instead of one whole split (higher = better)
        "preempt_p99_improvement": round(
            fused_reaction["p99_ms"]
            / max(chunked_reaction["p99_ms"], 1e-3), 2),
        "fused_scan_ms": round(fused_scan_ms, 2),
        "chunked_scan_ms": round(chunked_scan_ms, 2),
        "warm_overhead_pct": round(
            (chunked_scan_ms / max(fused_scan_ms, 1e-9) - 1.0) * 100, 1),
    }


def _measure_query_batch(iters: int) -> dict:
    """Config #14: device-side multi-query batching (ROADMAP item 2).
    Six distinct shape-compatible dashboard panels — different time
    windows, shared sort + agg shape — over ONE warm resident split,
    executed as ONE stacked dispatch per round (counter-asserted: the
    kernel-launch delta per batched round must be exactly 1), against a
    serial twin running the same panels one dispatch each, at group
    widths Q in {1, 2, 4, 8}. The scored acceptance claim: warm
    per-query p50 at Q=8 (one 8-wide round / 8) < 4x solo p50(Q=1) —
    each of the 8 queries sharing the dispatch lands for well under
    four solo rounds, while the round itself is counter-asserted to be
    a single kernel launch. (On the virtual CPU mesh the vmapped query
    axis executes lanes serially and the [Q, docs] working set spills
    host cache past bucket 4, so the whole-round latencies reported
    alongside are honest but CPU-bound; the dispatch-count reduction is
    the part that transfers to real accelerators.)"""
    from quickwit_tpu.index import SplitReader
    from quickwit_tpu.index.synthetic import HDFS_MAPPER, synthetic_hdfs_split
    from quickwit_tpu.observability.metrics import (
        SEARCH_KERNEL_LAUNCHES_TOTAL)
    from quickwit_tpu.query.ast import Range, RangeBound
    from quickwit_tpu.search import executor as ex
    from quickwit_tpu.search.leaf import prepare_single_split
    from quickwit_tpu.search.models import SearchRequest, SortField
    from quickwit_tpu.storage import StorageResolver

    docs = int(os.environ.get("BENCH_QBATCH_DOCS", 32_768))
    k = 10
    resolver = StorageResolver.for_test()
    storage = resolver.resolve("ram:///bench-qbatch")
    storage.put("q.split", synthetic_hdfs_split(docs, seed=700))
    reader = SplitReader(storage, "q.split")

    t0, half_day = 1_600_000_000, 43_200

    def panel(i):
        return SearchRequest(
            index_ids=["hdfs-logs"],
            query_ast=Range(
                "timestamp",
                lower=RangeBound((t0 + i * half_day) * 1_000_000, True),
                upper=RangeBound((t0 + (i + 8) * half_day) * 1_000_000,
                                 False)),
            max_hits=k,
            aggs={"per_hour": {"date_histogram": {
                "field": "timestamp", "fixed_interval": "1h"}}},
            sort_fields=(SortField("timestamp", "desc"),))

    n_panels = 6
    prepped = [prepare_single_split(panel(i), HDFS_MAPPER, reader, "q")
               for i in range(n_panels)]
    plans = [p for p, _a, _w in prepped]
    arrays = [a for _p, a, _w in prepped]
    assert len({p.structure_digest(k) for p in plans}) == 1, \
        "bench panels must be shape-compatible (one group key)"

    out: dict = {"n_panels": n_panels, "docs": docs, "widths": {}}
    for q in (1, 2, 4, 8):
        lane_plans = [plans[i % n_panels] for i in range(q)]
        lane_arrays = [arrays[i % n_panels] for i in range(q)]
        # warm: one compile per (structure, bucket), plus the solo twin
        ex.readback_plan_stacked(
            ex.dispatch_plan_stacked(lane_plans, k, lane_arrays))
        for p, a in zip(lane_plans, lane_arrays):
            ex.execute_plan(p, k, a)
        batched, serial = [], []
        for _ in range(iters):
            launches0 = SEARCH_KERNEL_LAUNCHES_TOTAL.get()
            t_round = time.monotonic()
            res = ex.readback_plan_stacked(ex.dispatch_plan_stacked(
                lane_plans, k, lane_arrays, cache_scalars=False))
            batched.append(time.monotonic() - t_round)
            launches = int(SEARCH_KERNEL_LAUNCHES_TOTAL.get() - launches0)
            assert launches == 1, \
                f"stacked round took {launches} dispatches (Q={q})"
            assert all(r is not None for r in res)
            t_round = time.monotonic()
            for p, a in zip(lane_plans, lane_arrays):
                ex.execute_plan(p, k, a)
            serial.append(time.monotonic() - t_round)
        b50 = _percentile(batched, 0.5)
        s50 = _percentile(serial, 0.5)
        out["widths"][f"q{q}"] = {
            "p50_ms": round(b50 * 1000, 2),
            "p99_ms": round(_percentile(batched, 0.99) * 1000, 2),
            "per_query_p50_ms": round(b50 * 1000 / q, 2),
            "serial_p50_ms": round(s50 * 1000, 2),
            "serial_p99_ms": round(_percentile(serial, 0.99) * 1000, 2),
            "speedup_p50": round(s50 / max(b50, 1e-9), 2),
            "dispatches_per_round": 1,
            "launches_per_query": round(1.0 / q, 3),
        }
    p1 = out["widths"]["q1"]["p50_ms"]
    pq8 = out["widths"]["q8"]["per_query_p50_ms"]
    assert pq8 < 4 * max(p1, 1e-6), \
        f"per-query p50 at Q=8 ({pq8}ms) not under 4x solo p50 ({p1}ms)"
    out["e2e_ms"] = pq8  # headline: warm per-query p50 inside an 8-group
    out["q8_per_query_vs_q1_p50"] = round(pq8 / max(p1, 1e-9), 2)
    return out


def _measure_flight_overhead(iters: int) -> dict:
    """Config #15: flight-recorder overhead on the c1-class warm path.
    One warm solo dispatch loop over a resident synthetic split, timed
    with the recorder ON (every dispatch emits compile/launch/readback
    events into the per-thread ring) and OFF (`FLIGHT.disable()`: emit is
    one attribute check). Samples alternate on/off to cancel thermal and
    cache drift; each sample times a small batch of executes. Scored
    acceptance: warm p50 overhead < 2%."""
    from quickwit_tpu.index.synthetic import HDFS_MAPPER
    from quickwit_tpu.observability.flight import FLIGHT
    from quickwit_tpu.query.ast import Term
    from quickwit_tpu.search import executor as ex
    from quickwit_tpu.search.leaf import prepare_single_split
    from quickwit_tpu.search.models import SearchRequest

    # the literal c1 workload (term + top-10 over the NUM_DOCS hdfs split,
    # cached split bytes shared with the c1 run): the <2% bound is against
    # the real warm path, not a toy corpus where the fixed per-dispatch
    # emit cost would dominate
    docs = int(os.environ.get("BENCH_FLIGHT_DOCS", NUM_DOCS))
    k = 10
    reader = _hdfs_reader(docs)
    request = SearchRequest(
        index_ids=["hdfs-logs"],
        query_ast=Term("severity_text", "ERROR"), max_hits=k)
    plan, arrays, _warm = prepare_single_split(
        request, HDFS_MAPPER, reader, "f")
    # warm: compile once, device arrays staged
    ex.execute_plan(plan, k, arrays)
    ex.execute_plan(plan, k, arrays)

    samples = max(iters * 3, 30)
    per_sample = 4
    on_times, off_times = [], []
    was_recording = FLIGHT.recording()
    try:
        for i in range(samples):
            enabled = (i % 2 == 0)
            (FLIGHT.enable if enabled else FLIGHT.disable)()
            t0 = time.monotonic()
            for _ in range(per_sample):
                ex.execute_plan(plan, k, arrays)
            (on_times if enabled else off_times).append(
                (time.monotonic() - t0) / per_sample)
    finally:
        (FLIGHT.enable if was_recording else FLIGHT.disable)()
    on50 = _percentile(on_times, 0.5)
    off50 = _percentile(off_times, 0.5)
    overhead_pct = (on50 - off50) / max(off50, 1e-9) * 100.0
    stats = FLIGHT.stats()
    assert stats["events"] > 0, "recorder captured nothing while enabled"
    assert overhead_pct < 2.0, \
        f"flight recorder warm overhead {overhead_pct:.2f}% >= 2%"
    return {
        "docs": docs,
        "samples_per_mode": samples // 2,
        "recording_p50_ms": round(on50 * 1000, 3),
        "disabled_p50_ms": round(off50 * 1000, 3),
        "warm_overhead_pct": round(overhead_pct, 2),
        "events_buffered": stats["events"],
        "e2e_ms": round(on50 * 1000, 3),
    }


def _run_all(iters: int, with_device_loops: bool = True) -> dict:
    results: dict = {}
    workloads = _workloads()
    for name, (request, mapper, reader_thunk) in workloads.items():
        t0 = time.monotonic()
        reader = reader_thunk()
        gen_s = time.monotonic() - t0
        stats = _measure_single_split(request, mapper, reader, iters,
                                      full=with_device_loops)
        stats["gen_s"] = round(gen_s, 1)
        results[name] = stats
        print(f"# {name}: {json.dumps(stats)}", file=sys.stderr)
    results["c5_otel_percentiles"] = _measure_batch_otel(
        max(3, iters // 3), full=with_device_loops)
    print(f"# c5_otel_percentiles: "
          f"{json.dumps(results['c5_otel_percentiles'])}", file=sys.stderr)
    if with_device_loops:  # parent run only: the child has no use for it
        results["c6_split_pruning"] = _measure_pruning(max(3, iters // 3))
        print(f"# c6_split_pruning: "
              f"{json.dumps(results['c6_split_pruning'])}", file=sys.stderr)
        results["c7_tenant_isolation"] = _measure_tenant_isolation()
        print(f"# c7_tenant_isolation: "
              f"{json.dumps(results['c7_tenant_isolation'])}", file=sys.stderr)
        results["c8_offload_scaling"] = _measure_offload_scaling()
        print(f"# c8_offload_scaling: "
              f"{json.dumps(results['c8_offload_scaling'])}", file=sys.stderr)
        results["c9_resident_warm"] = _measure_resident_warm(
            max(3, iters // 3))
        print(f"# c9_resident_warm: "
              f"{json.dumps(results['c9_resident_warm'])}", file=sys.stderr)
        results["c10_impact_ordered"] = _measure_impact_ordered(
            max(3, iters // 3))
        print(f"# c10_impact_ordered: "
              f"{json.dumps(results['c10_impact_ordered'])}", file=sys.stderr)
        results["c11_dashboard_qps"] = _measure_dashboard_qps(
            max(3, iters // 3))
        print(f"# c11_dashboard_qps: "
              f"{json.dumps(results['c11_dashboard_qps'])}", file=sys.stderr)
        results["c12_preemption"] = _measure_preemption()
        print(f"# c12_preemption: "
              f"{json.dumps(results['c12_preemption'])}", file=sys.stderr)
        results["c14_query_batch"] = _measure_query_batch(max(3, iters // 3))
        print(f"# c14_query_batch: "
              f"{json.dumps(results['c14_query_batch'])}", file=sys.stderr)
        results["c15_flight_recorder"] = _measure_flight_overhead(
            max(3, iters // 3))
        print(f"# c15_flight_recorder: "
              f"{json.dumps(results['c15_flight_recorder'])}",
              file=sys.stderr)
        c13 = _measure_multichip()
        if c13 is not None:
            results["c13_multichip"] = c13
            print(f"# c13_multichip: {json.dumps(c13)}", file=sys.stderr)
    return results


def _measure_multichip() -> "dict | None":
    """Config #13: the collective root merge vs the host-merge twin at
    1/2/4/8-device meshes — per-query host round-trips, readback bytes,
    warm p50/p99, and device≡host bit-identity on the c1 and c5 shapes.

    Runs `__graft_entry__.dryrun_multichip(8)` in a subprocess because the
    device count must be forced before jax backend init (this process has
    already initialized whatever platform the bench runs on) and parses
    its MULTICHIP_SCORED scoreboard line."""
    entry = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "__graft_entry__.py")
    try:
        run = subprocess.run(
            [sys.executable, entry, "8"],
            env={**os.environ, "QW_JAX_PLATFORM": "cpu"},
            capture_output=True, timeout=1200)
    except subprocess.TimeoutExpired:
        print("# c13_multichip timed out; omitting", file=sys.stderr)
        return None
    for line in run.stdout.decode().splitlines():
        if line.startswith("MULTICHIP_SCORED "):
            return json.loads(line[len("MULTICHIP_SCORED "):])
    print(f"# c13_multichip failed rc={run.returncode}: "
          f"{run.stderr.decode()[-300:]}", file=sys.stderr)
    return None


def _cpu_reference() -> "dict | None":
    """All configs on this package's CPU path in a subprocess."""
    try:
        run = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**os.environ, "QW_JAX_PLATFORM": "cpu",
                 "BENCH_CHILD_JSON": "1",
                 "BENCH_ITERS": str(max(5, ITERATIONS // 3))},
            capture_output=True, timeout=2400)
    except subprocess.TimeoutExpired:
        print("# cpu comparison run timed out; omitting measured ratios",
              file=sys.stderr)
        return None
    for line in run.stdout.decode().splitlines():
        if line.startswith("{"):
            return json.loads(line)
    print(f"# cpu comparison run failed rc={run.returncode}: "
          f"{run.stderr.decode()[-300:]}", file=sys.stderr)
    return None


def main() -> None:
    child_mode = bool(os.environ.get("BENCH_CHILD_JSON"))
    platform = _ensure_device_or_fall_back()

    from quickwit_tpu.utils.compile_cache import (
        enable_persistent_compile_cache)
    cache_dir = enable_persistent_compile_cache(
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    print(f"# compile cache: {cache_dir}", file=sys.stderr)

    if child_mode:
        # CPU comparison child: e2e p50 + batched throughput per config
        results = _run_all(ITERATIONS, with_device_loops=False)
        print(json.dumps({
            name: {"e2e_ms": s["e2e_ms"], "pipe_ms": s.get("pipe_ms")}
            for name, s in results.items()}))
        return

    results = _run_all(ITERATIONS)

    import jax
    import numpy as np
    device_kind = jax.devices()[0].device_kind

    # transport round-trip: fresh 4-byte H2D + blocking D2H. Under the
    # axon tunnel this is ~60 ms and it floors every 1-shot e2e number
    # (two serialized rounds: dispatch + readback); on a PCIe/ICI-attached
    # TPU host it is microseconds. Recorded so the e2e rows can be read
    # against the transport they were measured over.
    t0 = time.monotonic()
    probes = 3
    for i in range(probes):
        jax.device_get(jax.device_put(np.int32(i)))
    rtt_ms = (time.monotonic() - t0) * 1000 / probes / 2
    peak = _PEAK_HBM.get(device_kind)
    for stats in results.values():
        if peak and "hbm_gbps" in stats:
            stats["bw_util"] = round(stats["hbm_gbps"] * 1e9 / peak, 3)

    cpu = None
    if platform not in ("cpu", "cpu-fallback") and \
            not os.environ.get("BENCH_SKIP_CPU_COMPARE"):
        cpu = _cpu_reference()
    if cpu:
        for name, stats in results.items():
            if name not in cpu:
                continue
            entry = cpu[name]
            if not isinstance(entry, dict):  # legacy child format
                entry = {"e2e_ms": entry, "pipe_ms": None}
            cpu_e2e = entry["e2e_ms"]
            # the pipelined denominator is the CPU's own BEST concurrent-
            # query number (it gets the same multi-query batched path),
            # never the inflated 1-shot latency
            cpu_best = min(x for x in (cpu_e2e, entry.get("pipe_ms"))
                           if x is not None)
            stats["cpu_ms"] = cpu_e2e
            if entry.get("pipe_ms") is not None:
                stats["cpu_pipe_ms"] = entry["pipe_ms"]
            stats["vs_cpu_e2e"] = round(cpu_e2e / stats["e2e_ms"], 2)
            # .get() truthiness, not presence: dev_ms rounds to 0.0 when
            # the two-depth delta is noise-negative (floored to 1e-9 s)
            stats["vs_cpu_pipelined"] = round(
                cpu_best / stats["pipe_ms"], 2) \
                if stats.get("pipe_ms") else None
            stats["vs_cpu_device"] = round(
                cpu_best / stats["dev_ms"], 1) \
                if stats.get("dev_ms") else None
    for stats in results.values():
        # the C++ comparator as denominator — the strictest one: a single
        # modern core over pre-decoded arrays. Independent of the own-CPU
        # child run, so it survives BENCH_SKIP_CPU_COMPARE / child failure
        if stats.get("native_cpu_ms"):
            stats["vs_native_pipelined"] = round(
                stats["native_cpu_ms"] / stats["pipe_ms"], 2) \
                if stats.get("pipe_ms") else None
            stats["vs_native_device"] = round(
                stats["native_cpu_ms"] / stats["dev_ms"], 2) \
                if stats.get("dev_ms") else None

    details = {
        "platform": platform, "device_kind": device_kind,
        "peak_hbm_gbps": (peak / 1e9 if peak else None),
        "transport_rtt_ms": round(rtt_ms, 1),
        "pipeline_batch": PIPELINE_BATCH,
        "num_docs": NUM_DOCS, "configs": results,
    }
    if platform in ("cpu", "cpu-fallback"):
        # raw CPU-fallback ratio lives HERE, where its context (platform,
        # per-config numbers) is visible; the printed headline withholds
        # every ratio on fallback runs
        details["cpu_fallback_vs_1s_bound"] = round(
            1000.0 / results["flagship"]["e2e_ms"], 2)
    details_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAILS.json")
    with open(details_path, "w") as fh:
        json.dump(details, fh, indent=1)
    print(f"# full table written to {details_path}", file=sys.stderr)

    head = results["flagship"]
    note = os.environ.get("BENCH_PLATFORM_NOTE", platform)
    if head.get("cpu_ms"):
        vs = head["vs_cpu_pipelined"]
        native_note = ""
        if head.get("native_cpu_ms"):
            native_note = (f", native C++ single-core comparator "
                           f"{head['native_cpu_ms']}ms -> "
                           f"{head.get('vs_native_pipelined')}x pipelined/"
                           f"{head.get('vs_native_device')}x device")
        note = (f"{note}, {PIPELINE_BATCH} concurrent queries/dispatch, "
                f"dev p50 {head['dev_ms']}ms "
                f"({head.get('bw_util', 0) * 100:.0f}% HBM bw, "
                f"{head['vs_cpu_device']}x vs cpu-device), "
                f"e2e 1-shot {head['e2e_ms']}ms incl 2x{rtt_ms:.0f}ms "
                f"tunnel rtt, cpu denominator min(own-cpu 1-shot "
                f"{head['cpu_ms']:.0f}ms, own-cpu batched "
                f"{head.get('cpu_pipe_ms', head['cpu_ms']):.0f}ms)"
                f"{native_note}")
        value = head["pipe_ms"]
    elif head.get("vs_native_pipelined") \
            and platform not in ("cpu", "cpu-fallback"):
        # real device but the own-cpu child was unavailable: the native
        # comparator is the denominator. (A cpu-fallback run keeps the
        # 1s-headline-bound framing below — JAX-on-CPU is not the
        # production leaf path and a native ratio would misstate it.)
        vs = head["vs_native_pipelined"]
        note = (f"{note}, denominator: native C++ single-core comparator "
                f"{head['native_cpu_ms']}ms (own-cpu child unavailable)")
        value = head["pipe_ms"]
    else:
        vs = round(1000.0 / head["e2e_ms"], 2)
        note = f"{note}, vs 1s headline bound"
        value = head["e2e_ms"]
    headline = {
        "metric": "hdfs-logs leaf_search pipelined p50 (term+date_histogram"
                  f"+terms, {NUM_DOCS/1e6:g}M docs, 1 chip, {note})",
        "value": value,
        "unit": "ms",
        "vs_baseline": vs,
    }
    if platform in ("cpu", "cpu-fallback"):
        # honesty: JAX-on-CPU is not the production leaf path, so a CPU run
        # must not headline ANY ratio that reads like an accelerator result
        # — the headline leads with the caveat and carries latency only;
        # raw numbers stay in BENCH_DETAILS.json
        # (cpu_fallback_vs_1s_bound + per-config tables)
        headline["metric"] = ("no TPU available — CPU fallback: "
                              + headline["metric"])
        headline["vs_baseline"] = None
    print(json.dumps(headline))


if __name__ == "__main__":
    main()
