"""Benchmark: hdfs-logs leaf-search on the flagship workload.

Measures p50 end-to-end leaf_search latency on one real chip for the
BASELINE.json headline config: single-term query (severity_text:ERROR) +
top-10 hits + date_histogram(1d) + terms(severity) aggregation over an
hdfs-logs-shaped split (default 10M docs — the distributed-tutorial split
size; override with BENCH_NUM_DOCS).

Latency includes the full leaf path after warmup: plan lowering (host),
cached device arrays, jitted kernel execution, and the single batched
device→host readback of hits + agg states.

`vs_baseline`: when the TPU is reachable, this is the MEASURED ratio
cpu_p50 / tpu_p50 on identical inputs — this package's own CPU execution
of the same jitted leaf program (the honest north-star denominator per
BASELINE.json; the reference tantivy binary cannot be built here — no
Rust toolchain — see BASELINE.md). On cpu-fallback the ratio degrades to
1000ms / p50 against the reference's "sub-second" headline bound
(docs/overview/index.md:9) and the metric label says so.

Device-init robustness: the axon tunnel can wedge indefinitely inside
native code (in-process watchdogs never fire). The probe runs in killable
subprocesses: several short-deadline attempts with backoff rather than
one long gamble, surfacing each failure mode on stderr.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_DOCS = int(os.environ.get("BENCH_NUM_DOCS", 10_000_000))
ITERATIONS = int(os.environ.get("BENCH_ITERS", 30))
# total budget for device discovery, split into short killable probes
DEVICE_TIMEOUT_SECS = int(os.environ.get("BENCH_DEVICE_TIMEOUT", 180))
PROBE_DEADLINE_SECS = int(os.environ.get("BENCH_PROBE_DEADLINE", 60))
PROBE_BACKOFF_SECS = float(os.environ.get("BENCH_PROBE_BACKOFF", 5))


def _probe_device_once(deadline: float) -> "str | None":
    """One killable-subprocess device probe; returns platform or None."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=deadline)
    except subprocess.TimeoutExpired:
        print(f"# device probe: no response within {deadline:.0f}s "
              "(tunnel wedged or still initializing)", file=sys.stderr)
        return None
    if probe.returncode == 0:
        return probe.stdout.decode().strip().splitlines()[-1]
    print(f"# device probe failed rc={probe.returncode}: "
          f"{probe.stderr.decode()[-200:]}", file=sys.stderr)
    return None


def _ensure_device_or_fall_back() -> str:
    """Repeated short-deadline probes with backoff across the total budget;
    CPU fallback (via re-exec so the platform is set before backend init)
    only after every attempt failed."""
    if os.environ.get("QW_JAX_PLATFORM"):
        return os.environ["QW_JAX_PLATFORM"]
    budget_end = time.monotonic() + DEVICE_TIMEOUT_SECS
    attempt = 0
    while time.monotonic() < budget_end:
        attempt += 1
        remaining = budget_end - time.monotonic()
        deadline = min(PROBE_DEADLINE_SECS, max(remaining, 5.0))
        platform = _probe_device_once(deadline)
        if platform is not None:
            print(f"# device probe: {platform} (attempt {attempt})",
                  file=sys.stderr)
            return platform
        if time.monotonic() + PROBE_BACKOFF_SECS >= budget_end:
            break
        time.sleep(PROBE_BACKOFF_SECS)
    print(f"# device init failed after {attempt} probe(s) within "
          f"{DEVICE_TIMEOUT_SECS}s; falling back to CPU", file=sys.stderr)
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)],
              {**os.environ, "QW_JAX_PLATFORM": "cpu",
               "BENCH_PLATFORM_NOTE": "cpu-fallback"})
    return "unreachable"


def _measure(num_docs: int, iterations: int) -> dict:
    from __graft_entry__ import _flagship_request, _reader_for
    from quickwit_tpu.index.synthetic import HDFS_MAPPER
    from quickwit_tpu.search.leaf import leaf_search_single_split

    t0 = time.monotonic()
    reader = _reader_for(num_docs=num_docs, seed=7)
    gen_s = time.monotonic() - t0

    request = _flagship_request()

    t0 = time.monotonic()
    resp = leaf_search_single_split(request, HDFS_MAPPER, reader, "bench")
    warm_s = time.monotonic() - t0
    assert resp.num_hits > 0

    latencies = []
    for _ in range(iterations):
        t0 = time.monotonic()
        resp = leaf_search_single_split(request, HDFS_MAPPER, reader, "bench")
        latencies.append(time.monotonic() - t0)
    latencies.sort()
    return {
        "p50_ms": latencies[len(latencies) // 2] * 1000.0,
        "p90_ms": latencies[int(len(latencies) * 0.9)] * 1000.0,
        "gen_s": gen_s,
        "warm_s": warm_s,
        "num_hits": int(resp.num_hits),
    }


def _cpu_reference_p50() -> "float | None":
    """Measure the same workload on this package's CPU path in a subprocess
    (the platform is fixed at backend init, so it cannot run in-process)."""
    iters = max(5, ITERATIONS // 3)
    try:
        run = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env={**os.environ, "QW_JAX_PLATFORM": "cpu",
                 "BENCH_CHILD_JSON": "1", "BENCH_ITERS": str(iters)},
            capture_output=True, timeout=1200)
    except subprocess.TimeoutExpired:
        print("# cpu comparison run timed out; omitting measured ratio",
              file=sys.stderr)
        return None
    for line in run.stdout.decode().splitlines():
        if line.startswith("{"):
            return json.loads(line)["p50_ms"]
    print(f"# cpu comparison run failed rc={run.returncode}: "
          f"{run.stderr.decode()[-300:]}", file=sys.stderr)
    return None


def main() -> None:
    child_mode = bool(os.environ.get("BENCH_CHILD_JSON"))
    platform = _ensure_device_or_fall_back()
    stats = _measure(NUM_DOCS, ITERATIONS)
    p50_ms = stats["p50_ms"]

    print(f"# platform={platform} corpus={NUM_DOCS} docs, "
          f"gen={stats['gen_s']:.1f}s, "
          f"warmup(compile+transfer)={stats['warm_s']:.1f}s, "
          f"p50={p50_ms:.2f}ms p90={stats['p90_ms']:.2f}ms, "
          f"num_hits={stats['num_hits']}", file=sys.stderr)
    if child_mode:
        # parent bench parses this; not the driver-facing line
        print(json.dumps({"p50_ms": round(p50_ms, 2)}))
        return

    note = os.environ.get("BENCH_PLATFORM_NOTE", platform)
    cpu_p50 = None
    if platform not in ("cpu", "cpu-fallback") and \
            not os.environ.get("BENCH_SKIP_CPU_COMPARE"):
        cpu_p50 = _cpu_reference_p50()
    if cpu_p50 is not None:
        vs_baseline = round(cpu_p50 / p50_ms, 2)
        note = f"{note}, measured own-cpu p50 {cpu_p50:.0f}ms"
    else:
        # honest degradation: ratio vs the reference's 1s headline bound,
        # labeled as such (not a measured baseline)
        vs_baseline = round(1000.0 / p50_ms, 2)
        note = f"{note}, vs 1s headline bound"
    print(json.dumps({
        "metric": "hdfs-logs leaf_search p50 (term+date_histogram+terms, "
                  f"{NUM_DOCS/1e6:g}M docs, 1 chip, {note})",
        "value": round(p50_ms, 2),
        "unit": "ms",
        "vs_baseline": vs_baseline,
    }))


if __name__ == "__main__":
    main()
