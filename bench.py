"""Benchmark: hdfs-logs leaf-search on the flagship workload.

Measures p50 end-to-end leaf_search latency on one real chip for the
BASELINE.json headline config: single-term query (severity_text:ERROR) +
top-10 hits + date_histogram(1d) + terms(severity) aggregation over an
hdfs-logs-shaped split (default 10M docs — the distributed-tutorial split
size; override with BENCH_NUM_DOCS).

Latency includes the full leaf path after warmup: plan lowering (host),
cached device arrays, jitted kernel execution, and the single batched
device→host readback of hits + agg states.

`vs_baseline`: the reference's own headline number for this setup is
"sub-second search from object storage" (docs/overview/index.md:9; no
hard latency tables are published in-repo — BASELINE.md). vs_baseline is
therefore reported as 1000ms / p50_ms: how many times faster than the
reference's 1-second headline bound. The measured CPU-tantivy comparison
(north star: ≥8x) requires the reference binary, which this image cannot
build (no Rust toolchain) — see BASELINE.md.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_DOCS = int(os.environ.get("BENCH_NUM_DOCS", 10_000_000))
ITERATIONS = int(os.environ.get("BENCH_ITERS", 30))
DEVICE_TIMEOUT_SECS = int(os.environ.get("BENCH_DEVICE_TIMEOUT", 180))


def _ensure_device_or_fall_back() -> str:
    """TPU device init can hang indefinitely if the accelerator tunnel is
    wedged (and blocks in native code, so in-process watchdogs don't fire);
    probe it in a killable subprocess and fall back to CPU so the benchmark
    always emits its JSON line."""
    import subprocess
    if os.environ.get("QW_JAX_PLATFORM"):
        return os.environ["QW_JAX_PLATFORM"]
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, timeout=DEVICE_TIMEOUT_SECS)
        if probe.returncode == 0:
            platform = probe.stdout.decode().strip().splitlines()[-1]
            print(f"# device probe: {platform}", file=sys.stderr)
            return platform
        print(f"# device probe failed: {probe.stderr.decode()[-200:]}",
              file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"# device init exceeded {DEVICE_TIMEOUT_SECS}s; "
              "falling back to CPU", file=sys.stderr)
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)],
              {**os.environ, "QW_JAX_PLATFORM": "cpu",
               "BENCH_PLATFORM_NOTE": "cpu-fallback"})
    return "unreachable"


def main() -> None:
    platform = _ensure_device_or_fall_back()
    from __graft_entry__ import _flagship_request, _reader_for
    from quickwit_tpu.index.synthetic import HDFS_MAPPER
    from quickwit_tpu.search.leaf import leaf_search_single_split

    t0 = time.monotonic()
    reader = _reader_for(num_docs=NUM_DOCS, seed=7)
    gen_s = time.monotonic() - t0

    # the flagship workload definition is shared with __graft_entry__.entry()
    request = _flagship_request()

    # warmup: compile + device transfer
    t0 = time.monotonic()
    resp = leaf_search_single_split(request, HDFS_MAPPER, reader, "bench")
    warm_s = time.monotonic() - t0
    assert resp.num_hits > 0

    latencies = []
    for _ in range(ITERATIONS):
        t0 = time.monotonic()
        resp = leaf_search_single_split(request, HDFS_MAPPER, reader, "bench")
        latencies.append(time.monotonic() - t0)
    latencies.sort()
    p50_ms = latencies[len(latencies) // 2] * 1000.0
    p90_ms = latencies[int(len(latencies) * 0.9)] * 1000.0

    print(f"# corpus={NUM_DOCS} docs, gen={gen_s:.1f}s, "
          f"warmup(compile+transfer)={warm_s:.1f}s, "
          f"p50={p50_ms:.2f}ms p90={p90_ms:.2f}ms, "
          f"num_hits={resp.num_hits}", file=sys.stderr)
    note = os.environ.get("BENCH_PLATFORM_NOTE", platform)
    print(json.dumps({
        "metric": "hdfs-logs leaf_search p50 (term+date_histogram+terms, "
                  f"{NUM_DOCS/1e6:g}M docs, 1 chip, {note})",
        "value": round(p50_ms, 2),
        "unit": "ms",
        "vs_baseline": round(1000.0 / p50_ms, 2),
    }))


if __name__ == "__main__":
    main()
