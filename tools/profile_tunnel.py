"""Host-overhead profiling harness for the axon-tunnel TPU backend.

Round-5 findings (all measured on the real chip, TPU v5 lite via the
axon tunnel; the raw probe variants lived in prof_*.py during the
investigation and are consolidated here):

1. The per-query "host overhead" that kept flagship pipe_ms at ~56 ms
   (vs dev_ms 2.09) is a fixed ~60-65 ms per DISPATCH round-trip through
   the tunnel, serialized across dispatches — not Python, not readback
   size (packed output is 344 bytes), not program content (a 37-line
   HLO count-only program pays the same 65 ms as the 549-line flagship
   program when measured honestly with dispatch-then-device_get).
2. `jax.block_until_ready` on an output whose D2H copy has not been
   requested returns early under the axon platform — drain-style
   measurements that block only the last output report fantasy numbers
   (0.02 ms/exec). Only device_get-based timing is trustworthy.
3. Pipelining depth does NOT amortize the cost: dispatch-all/copy-all/
   get-all, interleaved depth-8/32, burst drains — all converge to
   ~61 ms/query because the tunnel serializes the rounds.
4. Queries executed INSIDE one dispatch are full speed: a
   `lax.fori_loop` running the kernel N deep costs ~2 ms/iteration
   (differenced across two depths), and the c5 batch runs 1000 splits
   in one dispatch for one ~65 ms round.

Conclusion: the only lever that works is putting more work per
dispatch. Hence `executor.dispatch_plan_multi` (vmap over stacked
per-query scalars, one packed readback) — which is also the
reference-faithful design: quickwit batches leaf requests per node
(`quickwit-search/src/leaf.rs:81` greedy_batch_split).

Run this script on the real chip to re-verify the numbers.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main():
    import jax
    import numpy as np
    from quickwit_tpu.utils.compile_cache import enable_persistent_compile_cache
    enable_persistent_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir, ".jax_cache"))
    from bench import _workloads
    from quickwit_tpu.search import executor as ex
    from quickwit_tpu.search.leaf import prepare_single_split

    # tunnel RTT: fresh 4-byte H2D put + blocking get
    t0 = time.monotonic()
    for i in range(4):
        jax.device_get(jax.device_put(np.int32(i)))
    rtt = (time.monotonic() - t0) / 4 / 2
    print(f"# tunnel one-way round estimate: {rtt*1e3:.1f} ms", file=sys.stderr)

    request, mapper, reader_thunk = _workloads()["flagship"]
    print("# generating corpus...", file=sys.stderr)
    reader = reader_thunk()
    plan, device_arrays, _ = prepare_single_split(request, mapper, reader, "b")
    k = request.start_offset + request.max_hits
    scalars, nd = ex._device_scalars(plan)
    args = (tuple(device_arrays), scalars, nd)
    packed_fn, _, _ = ex._get_packed_executor(plan, k, args)
    jax.device_get(packed_fn(*args))  # warm

    N = 24
    t0 = time.monotonic()
    outs = [packed_fn(*args) for _ in range(N)]
    for o in outs:
        o.copy_to_host_async()
    for o in outs:
        jax.device_get(o)
    print(f"# single-query dispatches, any pipelining pattern: "
          f"{(time.monotonic()-t0)/N*1e3:.1f} ms/q", file=sys.stderr)

    for B in (8, 16):
        t0 = time.monotonic()
        d = ex.dispatch_plan_multi(plan, k, device_arrays,
                                   [plan.scalars] * B)
        ex.readback_plan_multi(d)
        print(f"# multi-dispatch B={B} compile+first: "
              f"{time.monotonic()-t0:.1f}s", file=sys.stderr)
        NB = 4
        t0 = time.monotonic()
        ds = [ex.dispatch_plan_multi(plan, k, device_arrays,
                                     [plan.scalars] * B) for _ in range(NB)]
        for d in ds:
            ex.readback_plan_multi(d)
        dt = time.monotonic() - t0
        print(f"# multi-dispatch B={B}: {dt/NB*1e3:.1f} ms/batch = "
              f"{dt/NB/B*1e3:.2f} ms/query", file=sys.stderr)


if __name__ == "__main__":
    main()
