"""qwrace — deterministic happens-before race detection over the DST
scheduler.

The fourth analyzer in the family (qwlint / qwmc / qwir / qwrace, see
docs/static-analysis.md): FastTrack-style vector-clock detection running
under a gated, seeded PCT thread scheduler, so every detected race is
deterministic, shrinkable by the DST shrinker, and replayable
byte-identically from a canonical-JSON artifact. `bridge` cross-checks
the runtime lock-order witness graph against qwlint QW007's static
acquisition graph.
"""

from .detector import RaceDetector
from .harness import PctRace, race_from_dict
from .runtime import RaceRuntime, SchedulerAbort

__all__ = ["PctRace", "RaceDetector", "RaceRuntime", "SchedulerAbort",
           "race_from_dict"]
