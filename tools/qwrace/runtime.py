"""Gated PCT scheduler + instrumented sync primitives.

Loom-style cooperative serialization: every thread constructed through
`quickwit_tpu.common.sync` runs as a real OS thread, but exactly one holds
the scheduler token at any moment and every instrumented operation (lock
acquire/release, condition wait/notify, event set/wait, semaphore ops,
thread start/join, `note_read`/`note_write`) is a preemption point. At
each point the scheduler consults seeded PCT state — random per-thread
priorities plus `depth-1` pre-drawn priority-change steps — and either
lets the current thread continue or parks it and grants another. The
resulting interleaving is a pure function of the seed, so a detected race
replays byte-identically, and the rolling blake2b over the decision log
(`schedule_digest`) certifies it.

Timeout policy (the no-hang determinism rule): timeout *values* are
ignored entirely — several call sites derive them from real wall time,
which would leak nondeterminism. A timed wait blocks like an untimed one;
when NO thread is runnable, the earliest-blocked timed waiter is woken as
timed-out (a stall means its wakeup genuinely cannot arrive first). All
threads blocked with no timed waiter = deadlock: reported as a finding,
then the run aborts via `SchedulerAbort` (a BaseException so the
product's `except Exception` ladders cannot swallow it).

Uninstrumented ("wild") threads that touch an instrumented primitive are
lazily registered and gated from that point on; threads still parked when
the run ends are woken with the abort flag set so nothing leaks into the
next seed.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import random
import sys
import threading
from typing import Any, Callable, Optional

from quickwit_tpu.common.sync import SyncRuntime

from .detector import RaceDetector, vc_join

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SELF_FILES = (os.path.abspath(__file__),)


class SchedulerAbort(BaseException):
    """Run teardown/deadlock abort. BaseException on purpose: product
    code catches broad `Exception` in fan-out ladders; an aborting run
    must unwind through them."""


class _TState:
    __slots__ = ("tid", "name", "gate", "status", "timed", "block_seq",
                 "timeout_fired", "priority", "vc", "held", "final_vc",
                 "joiners", "real_thread")

    RUNNABLE = "runnable"
    BLOCKED = "blocked"
    FINISHED = "finished"

    def __init__(self, tid: int, name: str, priority: float,
                 vc: dict[int, int]):
        self.tid = tid
        self.name = name
        self.gate = threading.Event()
        self.status = _TState.RUNNABLE
        self.timed = False
        self.block_seq = 0
        self.timeout_fired = False
        self.priority = priority
        self.vc = vc
        self.held: list[Any] = []      # innermost-last instrumented locks
        self.final_vc: Optional[dict[int, int]] = None
        self.joiners: list["_TState"] = []
        self.real_thread: Optional[threading.Thread] = None


class RaceRuntime(SyncRuntime):
    """One instance per DST run; installed via `sync.use_runtime`."""

    def __init__(self, seed: int, depth: int = 3, horizon: int = 4096,
                 max_steps: int = 500_000,
                 detector: Optional[RaceDetector] = None):
        self.detector = detector if detector is not None else RaceDetector()
        self._rng = random.Random(seed)
        self._depth = max(int(depth), 1)
        self._max_steps = int(max_steps)
        points = min(self._depth - 1, max(horizon - 1, 0))
        self._change_points = set(
            self._rng.sample(range(1, horizon), points)) if points else set()
        self._step = 0
        self._block_counter = itertools.count(1)
        self._uid_counter = itertools.count()
        self._tid_counter = itertools.count(1)
        self._owner_names: dict[int, str] = {}     # id(obj) -> report name
        self._owner_refs: list[Any] = []           # pin ids against reuse
        self._owner_counts: dict[str, int] = {}
        self._order: list[_TState] = []            # registration order
        self._ident_map: dict[int, _TState] = {}
        self._reg_lock = threading.Lock()          # wild-thread admission
        self._pending: list[tuple[int, _TState]] = []
        self._active: Optional[_TState] = None
        self._aborted = False
        self._finalized = False
        self._hash = hashlib.blake2b(digest_size=16)
        self._main: Optional[_TState] = None

    # --- lifecycle ----------------------------------------------------------
    def install_main(self) -> None:
        """Register the calling thread (the DST op loop) as T0."""
        st = _TState(tid=0, name="main", priority=self._rng.random(),
                     vc={0: 1})
        self._order.append(st)
        self._ident_map[threading.get_ident()] = st
        self._active = st
        self._main = st

    def shutdown(self) -> None:
        """End of run (main thread active): abort and wake every parked
        thread so nothing leaks into the next seed; real-join seam
        threads briefly."""
        self._aborted = True
        self._finalized = True
        for st in self._order:
            if st.status != _TState.FINISHED:
                st.status = _TState.RUNNABLE
                st.gate.set()
        for st in self._order:
            t = st.real_thread
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5.0)

    def schedule_digest(self) -> str:
        return self._hash.hexdigest()

    @property
    def steps(self) -> int:
        return self._step

    @property
    def aborted(self) -> bool:
        return self._aborted

    # --- registration -------------------------------------------------------
    def _state(self) -> _TState:
        st = self._ident_map.get(threading.get_ident())
        if st is not None:
            return st
        # wild thread: admit it into the gated world from here on
        st = _TState(tid=next(self._tid_counter),
                     name=threading.current_thread().name,
                     priority=self._rng.random(), vc={})
        st.vc[st.tid] = 1
        with self._reg_lock:
            self._ident_map[threading.get_ident()] = st
            self._pending.append((st.tid, st))
        st.gate.wait()   # parked until a decision point admits + grants it
        if self._aborted:
            raise SchedulerAbort
        return st

    def _admit_pending(self) -> None:
        with self._reg_lock:
            pending, self._pending = self._pending, []
        for _, st in pending:
            self._order.append(st)

    # --- the scheduler ------------------------------------------------------
    def _enter_op(self, op: str, uid) -> _TState:
        st = self._state()
        if self._aborted:
            raise SchedulerAbort
        self._step += 1
        if self._step > self._max_steps:
            self.detector.budget_exhausted(self._step)
            self._abort_all()
            raise SchedulerAbort
        self._hash.update(
            f"{self._step}:{st.tid}:{op}:{uid}\n".encode())
        if self._step in self._change_points and self._active is not None:
            # PCT priority-change point: the running thread drops below
            # every base priority (base priorities are in (0, 1))
            self._active.priority = -float(self._step)
        self._maybe_switch(st)
        return st

    def _runnable(self) -> list[_TState]:
        self._admit_pending()
        return [s for s in self._order if s.status == _TState.RUNNABLE]

    def _pick(self, current: _TState) -> _TState:
        runnable = self._runnable()
        if runnable:
            return max(runnable, key=lambda s: (s.priority, -s.tid))
        timed = [s for s in self._order
                 if s.status == _TState.BLOCKED and s.timed]
        if timed:
            waiter = min(timed, key=lambda s: s.block_seq)
            waiter.timeout_fired = True
            waiter.status = _TState.RUNNABLE
            self._hash.update(f"timeout:{waiter.tid}\n".encode())
            return waiter
        self.detector.deadlock([
            {"tid": s.tid, "name": s.name}
            for s in self._order if s.status == _TState.BLOCKED])
        self._abort_all()
        raise SchedulerAbort

    def _maybe_switch(self, st: _TState) -> None:
        nxt = self._pick(st)
        if nxt is not st:
            self._grant(nxt, park=st)

    def _block(self, st: _TState, timed: bool) -> None:
        """Park the calling thread until a waker (or the stall-timeout
        policy) marks it runnable and a scheduling decision grants it."""
        st.status = _TState.BLOCKED
        st.timed = timed
        st.timeout_fired = False
        st.block_seq = next(self._block_counter)
        nxt = self._pick(st)
        self._grant(nxt, park=st)

    def _grant(self, nxt: _TState, park: _TState) -> None:
        park.gate.clear()
        self._active = nxt
        nxt.gate.set()
        park.gate.wait()
        if self._aborted:
            raise SchedulerAbort

    def _wake(self, st: _TState) -> None:
        if st.status == _TState.BLOCKED:
            st.status = _TState.RUNNABLE

    def _abort_all(self) -> None:
        self._aborted = True
        for st in self._order:
            if st.status != _TState.FINISHED:
                st.status = _TState.RUNNABLE
                st.gate.set()

    # --- naming -------------------------------------------------------------
    def _auto_name(self, kind: str, name: Optional[str]) -> str:
        if name:
            return name
        return f"<anon:{kind}#{next(self._uid_counter)}>"

    def owner_name(self, obj: Any) -> str:
        key = id(obj)
        name = self._owner_names.get(key)
        if name is None:
            base = type(obj).__name__
            n = self._owner_counts.get(base, 0)
            self._owner_counts[base] = n + 1
            name = f"{base}#{n}"
            self._owner_names[key] = name
            self._owner_refs.append(obj)
        return name

    def _site(self) -> str:
        frame = sys._getframe(1)
        while frame is not None:
            path = os.path.abspath(frame.f_code.co_filename)
            if path not in _SELF_FILES and not path.endswith(
                    os.path.join("quickwit_tpu", "common", "sync.py")):
                rel = os.path.relpath(path, _REPO_ROOT).replace(os.sep, "/")
                return f"{rel}:{frame.f_lineno}"
            frame = frame.f_back
        return "<unknown>"

    # --- HB bookkeeping shared by the primitives ----------------------------
    def _hb_release(self, st: _TState, obj_vc: dict[int, int]) -> None:
        vc_join(obj_vc, st.vc)
        st.vc[st.tid] = st.vc.get(st.tid, 0) + 1

    def _hb_acquire(self, st: _TState, obj_vc: dict[int, int]) -> None:
        vc_join(st.vc, obj_vc)

    def _record_acquisition(self, st: _TState, lock: "_LockBase") -> None:
        site = self._site()
        for outer in st.held:
            self.detector.witness(outer.qw_name, lock.qw_name, site)
        st.held.append(lock)

    def lockset(self, st: _TState) -> tuple:
        return tuple(lk.qw_name for lk in st.held)

    # --- SyncRuntime factory interface --------------------------------------
    def make_lock(self, name: Optional[str]):
        return _Lock(self, self._auto_name("lock", name))

    def make_rlock(self, name: Optional[str]):
        return _RLock(self, self._auto_name("rlock", name))

    def make_condition(self, lock: Any, name: Optional[str]):
        if lock is None:
            lock = _RLock(self, self._auto_name("rlock", name))
        return _Condition(self, lock, self._auto_name("cond", name))

    def make_event(self, name: Optional[str]):
        return _Event(self, self._auto_name("event", name))

    def make_semaphore(self, value: int, name: Optional[str]):
        return _Semaphore(self, value, self._auto_name("sem", name))

    def make_thread(self, target: Optional[Callable], args: tuple,
                    kwargs: dict, name: Optional[str],
                    daemon: Optional[bool]):
        return _Thread(self, target, args, kwargs, name, daemon)

    def note_access(self, owner: Any, field: str, is_write: bool) -> None:
        if self._finalized:
            return
        # name first: the schedule digest must hash a run-deterministic
        # token, never a raw id()
        name = self.owner_name(owner)
        st = self._enter_op("w" if is_write else "r",
                            f"{name}.{field}")
        self.detector.access(st.tid, st.vc, (name, field), is_write,
                             self._site(), self.lockset(st))

    def register_shared(self, obj: Any, name: str) -> None:
        key = id(obj)
        if key not in self._owner_names:
            n = self._owner_counts.get(name, 0)
            self._owner_counts[name] = n + 1
            self._owner_names[key] = f"{name}#{n}"
            self._owner_refs.append(obj)


# --- instrumented primitives -------------------------------------------------

class _LockBase:
    def __init__(self, rt: RaceRuntime, name: str):
        self._rt = rt
        self.qw_name = name
        self._uid = next(rt._uid_counter)
        self._vc: dict[int, int] = {}
        self._owner: Optional[_TState] = None
        self._count = 0
        self._waiters: list[_TState] = []

    def _plain(self) -> bool:
        # post-run fallback: after shutdown the process is back to a
        # single-threaded harness — keep the object usable, skip the
        # (dead) scheduler
        return self._rt._finalized

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._owner is not None

    def _acquire_free(self, st: _TState) -> None:
        self._owner = st
        self._count = 1
        self._rt._hb_acquire(st, self._vc)
        self._rt._record_acquisition(st, self)

    def _do_acquire(self, st: _TState, blocking: bool,
                    timed: bool, reentrant: bool) -> bool:
        while True:
            if self._owner is None:
                self._acquire_free(st)
                return True
            if reentrant and self._owner is st:
                self._count += 1
                return True
            if not blocking:
                return False
            self._waiters.append(st)
            try:
                self._rt._block(st, timed)
            finally:
                if st in self._waiters:
                    self._waiters.remove(st)
            if st.timeout_fired:
                return False

    def _do_release(self, st: _TState) -> None:
        if self._owner is not st:
            raise RuntimeError(
                f"release of {self.qw_name} by non-owner thread")
        self._count -= 1
        if self._count:
            return
        self._owner = None
        self._rt._hb_release(st, self._vc)
        if self in st.held:
            st.held.remove(self)
        for waiter in self._waiters:
            self._rt._wake(waiter)


class _Lock(_LockBase):
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._plain():
            self._count = 1
            return True
        st = self._rt._enter_op("lk+", self._uid)
        return self._do_acquire(st, blocking,
                                timed=timeout is not None and timeout >= 0,
                                reentrant=False)

    def release(self) -> None:
        if self._plain():
            self._count = 0
            return
        st = self._rt._enter_op("lk-", self._uid)
        self._do_release(st)


class _RLock(_LockBase):
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._plain():
            self._count += 1
            return True
        st = self._rt._enter_op("rl+", self._uid)
        return self._do_acquire(st, blocking,
                                timed=timeout is not None and timeout >= 0,
                                reentrant=True)

    def release(self) -> None:
        if self._plain():
            self._count = max(self._count - 1, 0)
            return
        st = self._rt._enter_op("rl-", self._uid)
        self._do_release(st)

    # Condition support (mirrors threading.RLock's private protocol)
    def _release_save(self, st: _TState):
        count = self._count
        self._count = 1
        self._do_release(st)
        return count

    def _acquire_restore(self, st: _TState, count: int) -> None:
        self._do_acquire(st, blocking=True, timed=False, reentrant=True)
        self._count = count

    def _is_owned_by(self, st: _TState) -> bool:
        return self._owner is st


class _Condition:
    def __init__(self, rt: RaceRuntime, lock: Any, name: str):
        self._rt = rt
        self.qw_name = name
        self._uid = next(rt._uid_counter)
        self._lock = lock
        self._vc: dict[int, int] = {}
        # (state, record) FIFO; record: {"notified": bool}
        self._waiters: list[tuple[_TState, dict]] = []

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def acquire(self, *a, **k):
        return self._lock.acquire(*a, **k)

    def release(self):
        return self._lock.release()

    def _owned(self, st: _TState) -> bool:
        return getattr(self._lock, "_owner", None) is st

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._rt._finalized:
            return False
        st = self._rt._enter_op("cw", self._uid)
        if not self._owned(st):
            raise RuntimeError("cannot wait on un-acquired condition")
        record = {"notified": False}
        self._waiters.append((st, record))
        if isinstance(self._lock, _RLock):
            saved = self._lock._release_save(st)
        else:
            saved = None
            self._lock._do_release(st)
        try:
            while not record["notified"]:
                self._rt._block(st, timed=timeout is not None)
                if st.timeout_fired:
                    break
        finally:
            if (st, record) in self._waiters:
                self._waiters.remove((st, record))
            if record["notified"]:
                self._rt._hb_acquire(st, self._vc)
            # re-acquire exactly like threading.Condition does
            if saved is not None:
                self._lock._acquire_restore(st, saved)
            else:
                self._lock._do_acquire(st, blocking=True, timed=False,
                                       reentrant=False)
        return bool(record["notified"])

    def wait_for(self, predicate: Callable[[], Any],
                 timeout: Optional[float] = None):
        result = predicate()
        while not result:
            if not self.wait(timeout) and timeout is not None:
                return predicate()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        if self._rt._finalized:
            return
        st = self._rt._enter_op("cn", self._uid)
        if not self._owned(st):
            raise RuntimeError("cannot notify on un-acquired condition")
        vc_join(self._vc, st.vc)
        st.vc[st.tid] = st.vc.get(st.tid, 0) + 1
        woken = 0
        for waiter, record in self._waiters:
            if record["notified"]:
                continue
            record["notified"] = True
            self._rt._wake(waiter)
            woken += 1
            if woken >= n:
                break

    def notify_all(self) -> None:
        self.notify(n=len(self._waiters) or 1)


class _Event:
    def __init__(self, rt: RaceRuntime, name: str):
        self._rt = rt
        self.qw_name = name
        self._uid = next(rt._uid_counter)
        self._flag = False
        self._vc: dict[int, int] = {}
        self._waiters: list[_TState] = []

    def is_set(self) -> bool:
        if self._flag and not self._rt._finalized:
            # an observed set() is a synchronization edge even through
            # the non-blocking read (FakeClock.wait polls this way)
            st = self._rt._ident_map.get(threading.get_ident())
            if st is not None:
                vc_join(st.vc, self._vc)
        return self._flag

    def set(self) -> None:
        if self._rt._finalized:
            self._flag = True
            return
        st = self._rt._enter_op("ev+", self._uid)
        self._flag = True
        vc_join(self._vc, st.vc)
        st.vc[st.tid] = st.vc.get(st.tid, 0) + 1
        for waiter in self._waiters:
            self._rt._wake(waiter)

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._rt._finalized:
            return self._flag
        st = self._rt._enter_op("evw", self._uid)
        while not self._flag:
            self._waiters.append(st)
            try:
                self._rt._block(st, timed=timeout is not None)
            finally:
                if st in self._waiters:
                    self._waiters.remove(st)
            if st.timeout_fired:
                return self._flag
        self._rt._hb_acquire(st, self._vc)
        return True


class _Semaphore:
    def __init__(self, rt: RaceRuntime, value: int, name: str):
        self._rt = rt
        self.qw_name = name
        self._uid = next(rt._uid_counter)
        self._value = int(value)
        self._vc: dict[int, int] = {}
        self._waiters: list[_TState] = []

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        if self._rt._finalized:
            self._value -= 1
            return True
        st = self._rt._enter_op("sm+", self._uid)
        while self._value <= 0:
            if not blocking:
                return False
            self._waiters.append(st)
            try:
                self._rt._block(st, timed=timeout is not None)
            finally:
                if st in self._waiters:
                    self._waiters.remove(st)
            if st.timeout_fired:
                return False
        self._value -= 1
        self._rt._hb_acquire(st, self._vc)
        return True

    def release(self, n: int = 1) -> None:
        if self._rt._finalized:
            self._value += n
            return
        st = self._rt._enter_op("sm-", self._uid)
        self._value += n
        vc_join(self._vc, st.vc)
        st.vc[st.tid] = st.vc.get(st.tid, 0) + 1
        for waiter in self._waiters:
            self._rt._wake(waiter)


class _Thread:
    def __init__(self, rt: RaceRuntime, target: Optional[Callable],
                 args: tuple, kwargs: dict, name: Optional[str],
                 daemon: Optional[bool]):
        self._rt = rt
        self._target = target
        self._args = args
        self._kwargs = kwargs
        self.name = name or f"qwrace-{next(rt._uid_counter)}"
        self.daemon = True if daemon is None else daemon
        self._st: Optional[_TState] = None
        self._real: Optional[threading.Thread] = None

    def start(self) -> None:
        rt = self._rt
        if rt._finalized:
            self._real = threading.Thread(
                target=self._target, args=self._args, kwargs=self._kwargs,
                name=self.name, daemon=self.daemon)
            self._real.start()
            return
        parent = rt._enter_op("th+", 0)
        child = _TState(tid=next(rt._tid_counter), name=self.name,
                        priority=rt._rng.random(), vc=dict(parent.vc))
        child.vc[child.tid] = 1
        parent.vc[parent.tid] = parent.vc.get(parent.tid, 0) + 1
        self._st = child
        rt._order.append(child)

        def _child_main() -> None:
            with rt._reg_lock:
                rt._ident_map[threading.get_ident()] = child
            child.gate.wait()
            try:
                if not rt._aborted and self._target is not None:
                    self._target(*self._args, **self._kwargs)
            except SchedulerAbort:
                return
            finally:
                if not rt._aborted:
                    self._finish(child)

        self._real = threading.Thread(target=_child_main, name=self.name,
                                      daemon=self.daemon)
        child.real_thread = self._real
        self._real.start()

    def _finish(self, child: _TState) -> None:
        rt = self._rt
        rt._hash.update(f"fin:{child.tid}\n".encode())
        child.final_vc = dict(child.vc)
        child.status = _TState.FINISHED
        for joiner in child.joiners:
            rt._wake(joiner)
        # dying grant: hand the token on without parking
        try:
            nxt = rt._pick(child)
        except SchedulerAbort:
            return
        rt._active = nxt
        nxt.gate.set()

    def join(self, timeout: Optional[float] = None) -> None:
        rt = self._rt
        if rt._finalized or self._st is None:
            if self._real is not None:
                self._real.join(timeout)
            return
        st = rt._enter_op("thj", self._st.tid)
        child = self._st
        while child.status != _TState.FINISHED:
            child.joiners.append(st)
            try:
                rt._block(st, timed=timeout is not None)
            finally:
                if st in child.joiners:
                    child.joiners.remove(st)
            if st.timeout_fired:
                return
        if child.final_vc is not None:
            vc_join(st.vc, child.final_vc)

    def is_alive(self) -> bool:
        if self._st is not None:
            return self._st.status != _TState.FINISHED
        return self._real is not None and self._real.is_alive()
