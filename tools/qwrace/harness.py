"""qwrace ↔ DST glue: the PCT race controller `run_scenario` accepts.

`PctRace` is the `race=` argument for `quickwit_tpu.dst.harness.
run_scenario/sweep/shrink/replay`: per run it derives a scheduler seed
from the DST seed, builds a fresh `RaceRuntime` + `RaceDetector`, installs
them through the `common/sync.py` seam for the run's whole lifetime
(cluster build included — a lock created outside the runtime would be
invisible to happens-before and produce false races), and converts
detector findings into DST `Violation`s (invariant `data_race` /
`race_deadlock` / `race_scheduler`) so the existing shrinker and artifact
machinery apply unchanged.

The controller also unions each run's lock-order witness edges, feeding
`tools/qwrace/bridge.py`'s static↔dynamic conformance check.
"""

from __future__ import annotations

import hashlib
import os
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Iterator, Optional

from quickwit_tpu.common import sync
from quickwit_tpu.dst.invariants import Violation

from .detector import RaceDetector
from .runtime import RaceRuntime, SchedulerAbort

RACE_INVARIANTS = ("data_race", "race_deadlock", "race_scheduler")

# planted-race switches (mandatory self-test of the detection pipeline):
# read at object construction time by ThresholdBox / WorkerPool, so they
# must be pinned in the artifact and re-applied by replay — an artifact
# must reproduce from the file ALONE, not from ambient environment
BREAK_ENV_VARS = ("QW_RACE_BREAK_THRESHOLD", "QW_RACE_BREAK_POOL")


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes")


def _scheduler_seed(seed: int, salt: str) -> int:
    digest = hashlib.blake2b(f"{salt}:{seed}".encode(),
                             digest_size=8).digest()
    return int.from_bytes(digest, "big")


class ActiveRace:
    """Per-run state; created by `PctRace.begin(seed)`."""

    abort_exc = SchedulerAbort

    def __init__(self, config: "PctRace", seed: int):
        self.config = config
        self.detector = RaceDetector()
        self.runtime = RaceRuntime(
            seed=_scheduler_seed(seed, config.seed_salt),
            depth=config.depth, horizon=config.horizon,
            max_steps=config.max_steps, detector=self.detector)
        self._finalized = False

    @contextmanager
    def activate(self) -> Iterator["ActiveRace"]:
        previous = sync.set_runtime(self.runtime)
        self.runtime.install_main()
        # pin the planted-race env switches to the CONTROLLER's recorded
        # values for the run's duration: replay of a break-flag artifact
        # reproduces in a fresh process with a clean environment
        saved = {name: os.environ.get(name) for name in BREAK_ENV_VARS}
        for name in BREAK_ENV_VARS:
            if self.config.break_flags.get(name):
                os.environ[name] = "1"
            else:
                os.environ.pop(name, None)
        try:
            yield self
        finally:
            sync.set_runtime(previous)
            for name, value in saved.items():
                if value is None:
                    os.environ.pop(name, None)
                else:
                    os.environ[name] = value

    def before_op(self, step: int) -> None:
        self.detector.set_op_step(step)

    def finalize(self) -> None:
        """Idempotent end-of-run teardown: abort + wake parked threads,
        flip the instrumented primitives into plain fallback mode (so
        `cluster.close()` still works), and fold this run's witness
        edges into the sweep-level union."""
        if self._finalized:
            return
        self._finalized = True
        self.runtime.shutdown()
        for edge, site in self.detector.witness_edges.items():
            self.config.witness_union.setdefault(edge, site)

    def violations(self) -> list[Violation]:
        out = []
        for finding in self.detector.findings():
            kind = finding.get("kind", "")
            if kind == "deadlock":
                invariant = "race_deadlock"
            elif kind == "scheduler_budget_exhausted":
                invariant = "race_scheduler"
            else:
                invariant = "data_race"
            out.append(Violation(invariant=invariant,
                                 step=int(finding.get("op_step", 0)),
                                 details=finding))
        return out

    def trace_event(self) -> dict[str, Any]:
        return {"steps": self.runtime.steps,
                "schedule_digest": self.runtime.schedule_digest(),
                "findings": len(self.detector.findings()),
                "witness_edges": len(self.detector.witness_edges)}


@dataclass
class PctRace:
    """The `race=` controller: seeded PCT schedule exploration. One
    instance can drive a whole sweep — `begin` hands out fresh per-run
    state; `witness_union` accumulates lock-order edges across runs."""

    depth: int = 3
    horizon: int = 4096
    max_steps: int = 500_000
    seed_salt: str = "qwrace"
    # None = snapshot the ambient QW_RACE_BREAK_* environment once, at
    # construction; an explicit dict (replay) overrides the environment
    break_flags: Optional[dict[str, bool]] = None

    def __post_init__(self) -> None:
        self.witness_union: dict[tuple[str, str], str] = {}
        if self.break_flags is None:
            self.break_flags = {name: _env_flag(name)
                                for name in BREAK_ENV_VARS}

    def begin(self, seed: int) -> ActiveRace:
        return ActiveRace(self, seed)

    def to_dict(self) -> dict[str, Any]:
        return {"pct": {"depth": self.depth, "horizon": self.horizon,
                        "max_steps": self.max_steps,
                        "seed_salt": self.seed_salt,
                        "break_flags": {k: bool(v) for k, v
                                        in sorted(self.break_flags.items())
                                        if v}}}


def race_from_dict(data: Optional[dict[str, Any]]) -> Optional[PctRace]:
    """Reconstruct the controller from an artifact's `race` section —
    the hook `dst replay` uses so a race artifact re-executes from the
    file alone."""
    if not data:
        return None
    pct = data.get("pct", {})
    return PctRace(depth=int(pct.get("depth", 3)),
                   horizon=int(pct.get("horizon", 4096)),
                   max_steps=int(pct.get("max_steps", 500_000)),
                   seed_salt=str(pct.get("seed_salt", "qwrace")),
                   break_flags={str(k): bool(v) for k, v
                                in pct.get("break_flags", {}).items()})


# --- SARIF ------------------------------------------------------------------

QWRACE_RULES = {
    "QWRACE001": "data race: conflicting accesses with no happens-before "
                 "order",
    "QWRACE002": "deadlock: every instrumented thread blocked with no "
                 "timed waiter",
    "QWRACE003": "lock-graph scope gap: runtime lock-order edge absent "
                 "from qwlint QW007's static graph",
}


def findings_to_sarif_results(findings: list[dict[str, Any]],
                              bridge_gaps: Optional[list[dict]] = None
                              ) -> list[dict]:
    """Map detector findings (+ bridge scope gaps) onto the shared
    `tools/sarif.py` result shape."""
    results: list[dict] = []
    for f in findings:
        kind = f.get("kind", "")
        if kind == "deadlock":
            results.append({
                "ruleId": "QWRACE002",
                "message": "deadlock: blocked threads "
                           + ", ".join(b["name"] for b in f["blocked"]),
                "site": "scheduler",
            })
            continue
        if kind == "scheduler_budget_exhausted":
            results.append({
                "ruleId": "QWRACE002",
                "message": f"scheduler budget exhausted after "
                           f"{f['steps']} steps (livelock suspect)",
                "site": "scheduler",
            })
            continue
        site = f["access"]["site"]
        path, _, line = site.rpartition(":")
        results.append({
            "ruleId": "QWRACE001",
            "message": f"{f['kind']} race on {f['object']}.{f['field']}: "
                       f"{f['access']['site']} "
                       f"(locks {f['access']['lockset'] or 'none'}) vs "
                       f"{f['previous']['site']} "
                       f"(locks {f['previous']['lockset'] or 'none'})",
            "file": path or site,
            "line": int(line) if line.isdigit() else None,
            "id": f"{f['kind']}:{f['object']}.{f['field']}",
        })
    for gap in bridge_gaps or []:
        results.append({
            "ruleId": "QWRACE003",
            "message": f"runtime lock-order edge {gap['held']} -> "
                       f"{gap['acquired']} (witnessed at {gap['site']}) "
                       "is absent from QW007's static graph",
            "site": f"{gap['held']}->{gap['acquired']}",
        })
    return results
