"""CLI: ``python -m tools.qwrace {sweep,replay,bridge,selftest,check}``.

- ``sweep --scenario fanout --seeds 10`` explores PCT schedules over a
  DST scenario with happens-before race detection; exit 1 on any race
  finding or lock-graph scope gap. ``--sarif PATH`` writes the findings
  through the shared ``tools/sarif.py`` emitter.
- ``replay path/to/artifact.json`` re-executes a race artifact from its
  contents alone (schedule seed, PCT config, and planted-race switches
  are all pinned inside); exit 1 unless the trace digest matches
  byte-for-byte AND the recorded violation fires again.
- ``bridge`` runs a clean sweep purely to collect the runtime lock-order
  witness graph and cross-checks it against qwlint QW007's static graph;
  exit 1 on a scope gap (see ``tools/qwrace/bridge.py``).
- ``selftest`` is the mandatory pipeline proof: for each planted race
  switch (``QW_RACE_BREAK_THRESHOLD``, ``QW_RACE_BREAK_POOL``) it must
  find the race within a bounded seed budget, shrink it, and replay the
  artifact byte-identically. A selftest failure means the detector — not
  the code under test — regressed.
- ``check`` is the qwcheck gate: bridge conformance + a short clean
  sweep (no races tolerated) in one exit code.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Any, Optional

from quickwit_tpu.dst.artifact import load_artifact
from quickwit_tpu.dst.harness import replay, scenario_by_name, sweep

from .bridge import compare
from .harness import (BREAK_ENV_VARS, QWRACE_RULES, PctRace,
                      findings_to_sarif_results)


def _race_findings(summary: dict[str, Any]) -> list[dict]:
    """Extract the raw detector findings from a sweep summary's
    violation entries (the `details` of race-invariant violations)."""
    out = []
    for entry in summary["violations"]:
        details = entry.get("violation", {}).get("details", {})
        if details:
            out.append(details)
    return out


def _emit_sarif(path: str, findings: list[dict],
                gaps: Optional[list[dict]] = None) -> None:
    from tools.sarif import write_sarif
    results = findings_to_sarif_results(findings, gaps)
    write_sarif(Path(path), "qwrace", QWRACE_RULES, results)


def _pct_from_args(args: argparse.Namespace,
                   break_flags: Optional[dict[str, bool]] = None) -> PctRace:
    return PctRace(depth=args.depth, horizon=args.horizon,
                   max_steps=args.max_steps, break_flags=break_flags)


def _cmd_sweep(args: argparse.Namespace) -> int:
    scenario = scenario_by_name(args.scenario)
    race = _pct_from_args(args)
    summary = sweep(scenario, seeds=args.seeds, start_seed=args.start_seed,
                    artifacts_dir=args.artifacts_dir,
                    stop_on_first=not args.keep_going, race=race)
    report = compare(race.witness_union)
    out = {"sweep": {k: v for k, v in summary.items()
                     if k != "violations"},
           "violations": summary["violations"],
           "bridge": report,
           "ok": summary["ok"] and report["conforms"]}
    if args.sarif:
        _emit_sarif(args.sarif, _race_findings(summary), report["gaps"])
    if args.json:
        print(json.dumps(out, sort_keys=True, indent=2))
    else:
        print(f"qwrace sweep: scenario={scenario.name} seeds={args.seeds} "
              f"passed={len(summary['passed'])} "
              f"violations={len(summary['violations'])} "
              f"bridge={'conforms' if report['conforms'] else 'GAPS'}")
        for entry in summary["violations"]:
            line = f"  seed {entry['seed']}: {entry['invariant']}"
            details = entry.get("violation", {}).get("details", {})
            if details.get("object"):
                line += f" on {details['object']}.{details.get('field')}"
            if "ops_after_shrink" in entry:
                line += (f" (shrunk {entry['ops_before_shrink']}"
                         f"→{entry['ops_after_shrink']} ops)")
            if "artifact" in entry:
                line += f" -> {entry['artifact']}"
            print(line)
        for gap in report["gaps"]:
            print(f"  scope gap: {gap['held']} -> {gap['acquired']} "
                  f"(witnessed at {gap['site']})")
    return 0 if out["ok"] else 1


def _cmd_replay(args: argparse.Namespace) -> int:
    artifact = load_artifact(args.artifact)
    result, digest_match = replay(artifact)
    expected = artifact["violation"]["invariant"]
    reproduced = any(v.invariant == expected for v in result.violations)
    out = {
        "seed": result.seed,
        "scenario": result.scenario.name,
        "digest": result.digest,
        "expected_digest": artifact["trace_digest"],
        "digest_match": digest_match,
        "expected_violation": expected,
        "violation_reproduced": reproduced,
        "race": artifact.get("race"),
        "violations": [v.to_dict() for v in result.violations],
    }
    if args.json:
        print(json.dumps(out, sort_keys=True, indent=2))
    else:
        status = ("REPLAYED byte-identically" if digest_match
                  else "TRACE DIVERGED")
        print(f"seed {result.seed} ({result.scenario.name}): {status}; "
              f"violation {expected!r} "
              f"{'reproduced' if reproduced else 'NOT reproduced'}")
    return 0 if (digest_match and reproduced) else 1


def _cmd_bridge(args: argparse.Namespace) -> int:
    scenario = scenario_by_name(args.scenario)
    race = _pct_from_args(args)
    summary = sweep(scenario, seeds=args.seeds, race=race,
                    shrink_violations=False)
    report = compare(race.witness_union)
    report["sweep_violations"] = len(summary["violations"])
    if args.sarif:
        _emit_sarif(args.sarif, _race_findings(summary), report["gaps"])
    if args.json:
        print(json.dumps(report, sort_keys=True, indent=2))
    else:
        print(f"qwrace bridge: witnessed={report['witnessed']} "
              f"static={report['static_edges']} "
              f"declared_used={len(report['declared_used'])} "
              f"anonymous={len(report['anonymous'])} "
              f"unwitnessed={len(report['unwitnessed'])} "
              f"{'CONFORMS' if report['conforms'] else 'SCOPE GAPS'}")
        for gap in report["gaps"]:
            print(f"  scope gap: {gap['held']} -> {gap['acquired']} "
                  f"(witnessed at {gap['site']})")
        for edge in report["declared_used"]:
            print(f"  declared: {edge['held']} -> {edge['acquired']}")
        for edge in report["unwitnessed"]:
            print(f"  unwitnessed static edge: {edge['held']} -> "
                  f"{edge['acquired']} ({edge['sites']} sites)")
    return 0 if report["conforms"] else 1


# planted switch -> the shared object its race lives on; selftest asserts
# the finding names the right object so a different (accidental) race
# cannot mask a broken plant
_PLANTED = {
    "QW_RACE_BREAK_THRESHOLD": "ThresholdBox",
    "QW_RACE_BREAK_POOL": "WorkerPool",
}


def run_selftest(budget: int = 10, depth: int = 3,
                 horizon: int = 4096) -> dict[str, Any]:
    """Find, shrink, and byte-identically replay both planted races.
    Pure function (no argparse) so tests and the qwcheck gate share it."""
    scenario = scenario_by_name("fanout")
    checks = []
    for flag in BREAK_ENV_VARS:
        race = PctRace(depth=depth, horizon=horizon,
                       break_flags={flag: True})
        summary = sweep(scenario, seeds=budget, race=race)
        doc: dict[str, Any] = {"flag": flag,
                               "expected_object": _PLANTED[flag]}
        hits = [e for e in summary["violations"]
                if e["invariant"] == "data_race"]
        if not hits:
            doc.update(ok=False, error=f"no data_race in {budget} seeds")
            checks.append(doc)
            continue
        entry = hits[0]
        details = entry["violation"]["details"]
        doc.update(seed=entry["seed"],
                   object=details.get("object", ""),
                   field=details.get("field", ""),
                   ops_before_shrink=entry.get("ops_before_shrink"),
                   ops_after_shrink=entry.get("ops_after_shrink"))
        result, digest_match = replay(entry["artifact_inline"])
        reproduced = any(v.invariant == "data_race"
                         for v in result.violations)
        doc.update(digest_match=digest_match, reproduced=reproduced,
                   ok=(digest_match and reproduced
                       and doc["object"].startswith(_PLANTED[flag])))
        checks.append(doc)
    return {"ok": all(c["ok"] for c in checks), "budget": budget,
            "checks": checks}


def _cmd_selftest(args: argparse.Namespace) -> int:
    doc = run_selftest(budget=args.budget, depth=args.depth,
                       horizon=args.horizon)
    if args.json:
        print(json.dumps(doc, sort_keys=True, indent=2))
    else:
        for c in doc["checks"]:
            if c["ok"]:
                print(f"qwrace selftest: {c['flag']}: found at seed "
                      f"{c['seed']} on {c['object']}.{c['field']} "
                      f"(shrunk {c['ops_before_shrink']}"
                      f"→{c['ops_after_shrink']} ops), replayed "
                      "byte-identically")
            else:
                print(f"qwrace selftest: {c['flag']}: FAIL — "
                      f"{c.get('error', c)}")
    return 0 if doc["ok"] else 1


def run_gate(seeds: int = 3) -> tuple[int, dict[str, Any]]:
    """The qwcheck gate: a short clean PCT sweep over the fanout scenario
    (no race findings tolerated) plus static↔dynamic lock-graph
    conformance over the witnessed edges."""
    race = PctRace()
    summary = sweep(scenario_by_name("fanout"), seeds=seeds, race=race,
                    shrink_violations=False)
    report = compare(race.witness_union)
    ok = summary["ok"] and report["conforms"]
    doc = {
        "ok": ok,
        "seeds": seeds,
        "race_violations": [
            {"seed": e["seed"], "invariant": e["invariant"],
             "details": e.get("violation", {}).get("details", {})}
            for e in summary["violations"]],
        "bridge": {k: report[k] for k in
                   ("conforms", "gaps", "declared_used", "anonymous",
                    "unwitnessed", "witnessed", "static_edges")},
    }
    return (0 if ok else 1), doc


def _cmd_check(args: argparse.Namespace) -> int:
    rc, doc = run_gate(seeds=args.seeds)
    if args.json:
        print(json.dumps(doc, sort_keys=True, indent=2))
    else:
        print(f"qwrace check: {'ok' if rc == 0 else 'FAIL'} "
              f"(seeds={doc['seeds']}, "
              f"races={len(doc['race_violations'])}, "
              f"bridge={'conforms' if doc['bridge']['conforms'] else 'GAPS'})")
    return rc


def _add_pct_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--depth", type=int, default=3,
                        help="PCT bug depth d (d-1 change points)")
    parser.add_argument("--horizon", type=int, default=4096,
                        help="PCT horizon k: change points drawn from the "
                             "first k decisions; match to trace length "
                             "for deep lock-order bugs")
    parser.add_argument("--max-steps", type=int, default=500_000,
                        help="scheduler step budget per run")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.qwrace",
        description="deterministic happens-before race detection over "
                    "the DST scheduler")
    sub = parser.add_subparsers(dest="command", required=True)

    p_sweep = sub.add_parser("sweep", help="PCT schedule exploration "
                                           "with race detection")
    p_sweep.add_argument("--scenario", default="fanout")
    p_sweep.add_argument("--seeds", type=int, default=10)
    p_sweep.add_argument("--start-seed", type=int, default=0)
    p_sweep.add_argument("--artifacts-dir", default=None)
    p_sweep.add_argument("--keep-going", action="store_true")
    p_sweep.add_argument("--sarif", default=None, metavar="PATH")
    p_sweep.add_argument("--json", action="store_true")
    _add_pct_args(p_sweep)
    p_sweep.set_defaults(fn=_cmd_sweep)

    p_replay = sub.add_parser("replay",
                              help="re-execute a race artifact")
    p_replay.add_argument("artifact")
    p_replay.add_argument("--json", action="store_true")
    p_replay.set_defaults(fn=_cmd_replay)

    p_bridge = sub.add_parser("bridge",
                              help="static↔dynamic lock-graph conformance")
    p_bridge.add_argument("--scenario", default="fanout")
    p_bridge.add_argument("--seeds", type=int, default=3)
    p_bridge.add_argument("--sarif", default=None, metavar="PATH")
    p_bridge.add_argument("--json", action="store_true")
    _add_pct_args(p_bridge)
    p_bridge.set_defaults(fn=_cmd_bridge)

    p_self = sub.add_parser("selftest",
                            help="planted-race pipeline proof")
    p_self.add_argument("--budget", type=int, default=10,
                        help="seed budget per planted race")
    p_self.add_argument("--json", action="store_true")
    _add_pct_args(p_self)
    p_self.set_defaults(fn=_cmd_selftest)

    p_check = sub.add_parser("check", help="the qwcheck gate: clean "
                                           "sweep + bridge conformance")
    p_check.add_argument("--seeds", type=int, default=3)
    p_check.add_argument("--json", action="store_true")
    p_check.set_defaults(fn=_cmd_check)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
