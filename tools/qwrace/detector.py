"""FastTrack-style vector-clock happens-before race detection.

The runtime (`tools/qwrace/runtime.py`) serializes every instrumented
thread and forwards each annotated shared access (`sync.note_read` /
`sync.note_write`) here with the thread's vector clock, lockset, and call
site. Two accesses to the same (owner, field) race when at least one is a
write and neither happens-before the other; happens-before is exactly the
edge set the runtime maintains: program order, lock release→acquire,
condition notify→wake, event set→wait, semaphore release→acquire, thread
start→first-op and last-op→join.

Locksets are NOT part of the race decision (pure happens-before — no
lockset-discipline false positives); they ride along in the report so a
fix can see which lock each side held.

The detector also accumulates the lock-order *witness graph*: every
nested acquisition observed at runtime, keyed by the seam lock names that
align with qwlint QW007's static node naming (`tools/qwrace/bridge.py`
cross-checks the two graphs).
"""

from __future__ import annotations

from typing import Any, Optional


def vc_join(a: dict[int, int], b: dict[int, int]) -> None:
    """a |= b, componentwise max, in place."""
    for tid, clk in b.items():
        if clk > a.get(tid, 0):
            a[tid] = clk


def _hb(clk: int, tid: int, vc: dict[int, int]) -> bool:
    """True when epoch (tid, clk) happens-before the observer clock
    `vc` — i.e. the observer has seen at least `clk` ticks of `tid`."""
    return clk <= vc.get(tid, 0)


class _VarState:
    __slots__ = ("write_tid", "write_clk", "write_site", "write_lockset",
                 "reads")

    def __init__(self) -> None:
        self.write_tid: Optional[int] = None
        self.write_clk = 0
        self.write_site = ""
        self.write_lockset: tuple = ()
        # tid -> (clk, site, lockset): reads since the last HB-ordered write
        self.reads: dict[int, tuple[int, str, tuple]] = {}


class RaceDetector:
    """One instance per run. All entry points are called with the gated
    scheduler token held (exactly one instrumented thread runs at a time),
    so no internal locking is needed and every structure iterates in
    deterministic insertion order."""

    def __init__(self) -> None:
        self._vars: dict[tuple[str, str], _VarState] = {}
        self.races: list[dict[str, Any]] = []
        self.errors: list[dict[str, Any]] = []
        # (held_name, acquired_name) -> first witnessed site
        self.witness_edges: dict[tuple[str, str], str] = {}
        self._race_keys: set = set()
        self._op_step = 0   # DST op index, stamped by the controller

    # --- context ------------------------------------------------------------
    def set_op_step(self, step: int) -> None:
        self._op_step = step

    # --- accesses -----------------------------------------------------------
    def access(self, tid: int, vc: dict[int, int], var: tuple[str, str],
               is_write: bool, site: str, lockset: tuple) -> None:
        state = self._vars.get(var)
        if state is None:
            state = self._vars[var] = _VarState()
        if is_write:
            if state.write_tid is not None and state.write_tid != tid \
                    and not _hb(state.write_clk, state.write_tid, vc):
                self._report("write-write", var, tid, site, lockset,
                             state.write_tid, state.write_site,
                             state.write_lockset)
            for rtid, (rclk, rsite, rlocks) in state.reads.items():
                if rtid != tid and not _hb(rclk, rtid, vc):
                    self._report("read-write", var, tid, site, lockset,
                                 rtid, rsite, rlocks)
            state.write_tid = tid
            state.write_clk = vc.get(tid, 0)
            state.write_site = site
            state.write_lockset = lockset
            state.reads.clear()
        else:
            if state.write_tid is not None and state.write_tid != tid \
                    and not _hb(state.write_clk, state.write_tid, vc):
                self._report("write-read", var, tid, site, lockset,
                             state.write_tid, state.write_site,
                             state.write_lockset)
            state.reads[tid] = (vc.get(tid, 0), site, lockset)

    def _report(self, kind: str, var: tuple[str, str], tid: int, site: str,
                lockset: tuple, other_tid: int, other_site: str,
                other_lockset: tuple) -> None:
        # dedup on the unordered site pair: the same textual race fires
        # once per report no matter how many thread pairs hit it
        key = (kind, var, frozenset((site, other_site)))
        if key in self._race_keys:
            return
        self._race_keys.add(key)
        self.races.append({
            "kind": kind,
            "object": var[0],
            "field": var[1],
            "op_step": self._op_step,
            "access": {"tid": tid, "site": site,
                       "lockset": sorted(lockset)},
            "previous": {"tid": other_tid, "site": other_site,
                         "lockset": sorted(other_lockset)},
            "common_locks": sorted(set(lockset) & set(other_lockset)),
        })

    # --- lock-order witnesses ----------------------------------------------
    def witness(self, held_name: str, acquired_name: str, site: str) -> None:
        if held_name == acquired_name:
            return
        self.witness_edges.setdefault((held_name, acquired_name), site)

    # --- scheduler errors ---------------------------------------------------
    def deadlock(self, blocked: list[dict[str, Any]]) -> None:
        self.errors.append({"kind": "deadlock", "op_step": self._op_step,
                            "blocked": blocked})

    def budget_exhausted(self, steps: int) -> None:
        self.errors.append({"kind": "scheduler_budget_exhausted",
                            "op_step": self._op_step, "steps": steps})

    # --- summary ------------------------------------------------------------
    def findings(self) -> list[dict[str, Any]]:
        return list(self.races) + list(self.errors)
