"""Static↔dynamic lock-graph bridge (the PR 12 conformance pattern,
applied to locks instead of protocol traces).

qwlint QW007 builds a *static* lock-acquisition graph by AST analysis;
the qwrace runtime witnesses the *dynamic* graph — every nested
acquisition that actually executed, named through the seam's QW007-style
lock names. The two must agree in one direction:

- a RUNTIME edge between two statically-identifiable locks that the
  static graph lacks is a **QW007 scope gap** — the analyzer missed an
  acquisition path that demonstrably happens (usually cross-procedural:
  a method called under lock A takes lock B internally). Gate-failing.
  Known cross-procedural edges are declared in `DECLARED_EDGES` with the
  call path that produces them; the declaration IS the audit trail.
- a runtime edge involving an anonymous lock (name outside QW007's
  `lock|mutex` naming convention) is reported as info: static analysis
  never claimed to see it.
- a STATIC edge never witnessed at runtime is coverage info, not a
  failure: the sweep simply never drove that path.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from tools.qwlint.core import FileContext, LintError, _iter_py_files
from tools.qwlint.rules import _LOCK_NAME_RE, _QW007_ALL_SHARED, LockOrder

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Cross-procedural acquisition edges QW007's intra-procedural traversal
# cannot see, each justified by the concrete call path. An entry here is
# reviewed like a suppression: the edge is REAL and must stay
# deadlock-consistent with the rest of the graph by manual argument.
DECLARED_EDGES: dict[tuple[str, str], str] = {
    ("TenantPartitionedCache._lock", "MemorySizedCache._lock"):
        "_partition()/_requota_locked() call part.resize()/evict hooks on "
        "the per-tenant MemorySizedCache while holding the partition-map "
        "lock; the inner cache never calls back out, so the order is "
        "acyclic by construction",
    ("Autoscaler._lock", "WorkerPool._lock"):
        "Autoscaler.tick() holds its reconcile lock across "
        "pool.size()/add_worker()/remove_worker()/snapshot(); WorkerPool "
        "methods never call back into the autoscaler, so the order is "
        "acyclic by construction",
    ("Autoscaler._lock", "OverloadController._lock"):
        "Autoscaler.tick()'s scale-down calm check reads "
        "overload.severity() under the reconcile lock; the controller is "
        "a leaf (pure EWMA state), so the order is acyclic",
    ("SearchService._lock", "WorkerPool._lock"):
        "SearcherContext.offload_dispatcher() lazily builds the pool and "
        "registers endpoint workers (pool.add_worker) under the context "
        "lock; the pool never re-enters the service, so the order is "
        "acyclic",
}


def static_lock_graph(root: Optional[str] = None
                      ) -> dict[tuple[str, str], list[dict]]:
    """QW007's full static acquisition graph (suppressed edges included)
    over quickwit_tpu/."""
    root = root or _REPO_ROOT
    package = os.path.join(root, "quickwit_tpu")
    rule = LockOrder()
    shared: dict = {}
    for path in _iter_py_files(package):
        relpath = os.path.relpath(os.path.abspath(path), root)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                ctx = FileContext(path, relpath, fh.read(), shared=shared)
        except LintError:
            continue
        rule.check(ctx)
    return shared.get(_QW007_ALL_SHARED, {})


def statically_identifiable(name: str) -> bool:
    """True when QW007's lock-identity regex would name this lock — the
    precondition for holding the static graph accountable for it."""
    if not name or name.startswith("<anon:"):
        return False
    return bool(_LOCK_NAME_RE.search(name.rsplit(".", 1)[-1]))


def compare(witness_edges: dict[tuple[str, str], str],
            static_edges: Optional[dict[tuple[str, str], list]] = None,
            declared: Optional[dict[tuple[str, str], str]] = None
            ) -> dict[str, Any]:
    """Cross-check the runtime witness graph against the static graph.

    Returns {"conforms", "gaps", "anonymous", "declared_used",
    "unwitnessed"}; `conforms` is False iff a statically-identifiable
    runtime edge is in neither the static graph nor DECLARED_EDGES."""
    if static_edges is None:
        static_edges = static_lock_graph()
    if declared is None:
        declared = DECLARED_EDGES
    gaps: list[dict] = []
    anonymous: list[dict] = []
    declared_used: list[dict] = []
    for (held, acquired), site in sorted(witness_edges.items()):
        entry = {"held": held, "acquired": acquired, "site": site}
        if not (statically_identifiable(held)
                and statically_identifiable(acquired)):
            anonymous.append(entry)
            continue
        if (held, acquired) in static_edges:
            continue
        if (held, acquired) in declared:
            declared_used.append(
                dict(entry, why=declared[(held, acquired)]))
            continue
        gaps.append(entry)
    witnessed = set(witness_edges)
    unwitnessed = [{"held": h, "acquired": a,
                    "sites": len(static_edges[(h, a)])}
                   for (h, a) in sorted(static_edges)
                   if (h, a) not in witnessed]
    return {
        "conforms": not gaps,
        "gaps": gaps,
        "anonymous": anonymous,
        "declared_used": declared_used,
        "unwitnessed": unwitnessed,
        "witnessed": len(witness_edges),
        "static_edges": len(static_edges),
    }
