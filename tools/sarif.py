"""Minimal SARIF 2.1.0 emitter shared by qwlint and qwir.

Emits only the mandatory skeleton CI annotators consume: one run, a
driver with rule metadata, and results carrying ruleId + message +
either a physical location (qwlint: file/line) or a logical location
(qwir: program/site — jaxpr findings have no source line by design).
Suppressed findings are carried with a `suppressions` entry so review
tooling can still render the certified-exception audit trail.
"""

from __future__ import annotations

import json
from pathlib import Path

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def sarif_log(tool: str, rules: dict[str, str],
              results: list[dict]) -> dict:
    """Build a SARIF log dict.

    `rules` maps ruleId -> short description. Each result dict needs
    `ruleId`, `message`, and either `file` (+ optional `line`) or
    `site`; optional keys: `id` (stable finding id), `suppressed`,
    `justification`.
    """
    out_results = []
    for r in results:
        entry: dict = {
            "ruleId": r["ruleId"],
            "level": "none" if r.get("suppressed") else "error",
            "message": {"text": r["message"]},
        }
        if r.get("id"):
            entry["partialFingerprints"] = {"stableId": r["id"]}
        if r.get("file"):
            phys = {"artifactLocation": {"uri": r["file"]}}
            if r.get("line"):
                phys["region"] = {"startLine": int(r["line"])}
            entry["locations"] = [{"physicalLocation": phys}]
        else:
            entry["locations"] = [{"logicalLocations": [
                {"fullyQualifiedName": r.get("site", r.get("id", "?"))}]}]
        if r.get("suppressed"):
            entry["suppressions"] = [{
                "kind": "inSource",
                "justification": r.get("justification", ""),
            }]
        out_results.append(entry)
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": tool,
                "rules": [{"id": rid,
                           "shortDescription": {"text": desc}}
                          for rid, desc in sorted(rules.items())],
            }},
            "results": out_results,
        }],
    }


def write_sarif(path: Path, tool: str, rules: dict[str, str],
                results: list[dict]) -> dict:
    log = sarif_log(tool, rules, results)
    path.write_text(json.dumps(log, indent=1, sort_keys=True) + "\n")
    return log
