"""CLI: `python -m tools.qwmc [check] [--model NAME] ...` / `replay FILE`.

Exit-code contract (qwlint-style, consumed by tests/test_qwmc.py and CI):
    0  every checked model verified clean to its bound
    1  at least one violation found (counterexample artifacts written when
       --artifact-dir is given), or a replay failed to reproduce
    2  usage error / unknown model / bad artifact

`check` (the default subcommand) exhaustively explores the selected
models at their pinned bounds; config flags tighten or loosen the bounds
and plant the known bugs (`--break-publish`, `--break-wal`,
`--stale-rejoin`, `--no-fsync`).  `replay` re-executes a counterexample
artifact from its contents alone.
"""

from __future__ import annotations

import argparse
import json
import sys

from .artifact import replay_artifact, save_counterexample
from .kernel import check_model
from .models import MODELS, build_model


def _model_config(args: argparse.Namespace, name: str) -> dict:
    config: dict = {}
    if args.crashes is not None:
        config["crashes"] = args.crashes
    if name == "replication":
        if args.ops is not None:
            config["ops"] = args.ops
        if args.break_wal:
            config["break_wal"] = True
        if args.stale_rejoin:
            config["stale_rejoin"] = True
        if args.no_fsync:
            config["fsync"] = False
    elif name == "checkpoint":
        if args.records is not None:
            config["records"] = args.records
        if args.break_publish:
            config["break_publish"] = True
    return config


def _cmd_check(args: argparse.Namespace) -> int:
    names = [args.model] if args.model else sorted(MODELS)
    for name in names:
        if name not in MODELS:
            print(f"qwmc: error: unknown model {name!r} "
                  f"(known: {sorted(MODELS)})", file=sys.stderr)
            return 2
    results = []
    artifacts = []
    for name in names:
        model = build_model(name, **_model_config(args, name))
        result = check_model(model, depth=args.depth,
                             symmetry=not args.no_symmetry)
        results.append(result)
        if result.violation is not None and args.artifact_dir:
            artifacts.append(save_counterexample(result, args.artifact_dir))
    ok = all(r.ok for r in results)
    if args.as_json:
        print(json.dumps({"ok": ok,
                          "results": [r.to_dict() for r in results],
                          "artifacts": artifacts},
                         indent=2, sort_keys=True))
    else:
        for result in results:
            status = "verified" if result.ok else "VIOLATION"
            bound = "" if result.complete else " (depth-bounded)"
            print(f"qwmc: {result.model}: {status} — {result.states} "
                  f"states, {result.transitions} transitions, depth "
                  f"{result.depth}{bound}")
            v = result.violation
            if v is not None:
                print(f"qwmc:   {v.kind}: {v.name}")
                print(f"qwmc:   path ({len(v.path)} steps): "
                      + " -> ".join(v.path))
                if v.cycle:
                    print(f"qwmc:   lasso cycle: " + " -> ".join(v.cycle))
        for path in artifacts:
            print(f"qwmc: wrote counterexample artifact {path}")
    return 0 if ok else 1


def _cmd_replay(args: argparse.Namespace) -> int:
    try:
        verdict = replay_artifact(args.artifact)
    except (OSError, ValueError, KeyError, json.JSONDecodeError) as exc:
        print(f"qwmc: error: {exc}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    else:
        status = "reproduced" if verdict["reproduced"] else "DIVERGED"
        print(f"qwmc: {verdict['model']}: {verdict['kind']}/"
              f"{verdict['name']} in {verdict['steps']} steps — {status}")
    return 0 if verdict["reproduced"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="qwmc",
        description="exhaustive model checking of the quickwit_tpu "
                    "replication/checkpoint protocols")
    sub = parser.add_subparsers(dest="command")

    check = sub.add_parser("check", help="explore models (default)")
    for p in (parser, check):
        p.add_argument("--model", default=None,
                       help=f"model to check (default: all of "
                            f"{sorted(MODELS)})")
        p.add_argument("--depth", type=int, default=None,
                       help="BFS depth bound (default: exhaust)")
        p.add_argument("--json", action="store_true", dest="as_json",
                       help="emit results as JSON on stdout")
        p.add_argument("--artifact-dir", default=None,
                       help="write counterexample artifacts here")
        p.add_argument("--no-symmetry", action="store_true",
                       help="disable symmetry reduction")
        p.add_argument("--crashes", type=int, default=None,
                       help="crash budget override (both models)")
        p.add_argument("--ops", type=int, default=None,
                       help="replication: ops per producer")
        p.add_argument("--records", type=int, default=None,
                       help="checkpoint: records to ingest")
        p.add_argument("--break-publish", action="store_true",
                       help="plant the QW_DST_BREAK_PUBLISH bug")
        p.add_argument("--break-wal", action="store_true",
                       help="plant the QW_DST_BREAK_WAL bug")
        p.add_argument("--stale-rejoin", action="store_true",
                       help="plant the pre-fix stale-leader-rejoin "
                            "semantics")
        p.add_argument("--no-fsync", action="store_true",
                       help="replication: model fsync=False durability")

    replay = sub.add_parser("replay",
                            help="re-execute a counterexample artifact")
    replay.add_argument("artifact")
    replay.add_argument("--json", action="store_true", dest="as_json")

    args = parser.parse_args(argv)
    if args.command == "replay":
        return _cmd_replay(args)
    return _cmd_check(args)


if __name__ == "__main__":
    sys.exit(main())
