"""The two protocol models, extracted from the implementation.

**ReplicationModel** — chained replication, from
`quickwit_tpu/ingest/ingester.py` + `wal.py` + the DST cluster's chain
wiring (`dst/cluster.py`):

* ``persist(p)`` / ``replica_persist`` / ``ack`` / ``rollback`` mirror the
  leader's critical section: append under the persist lock, replicate to
  the first alive non-leader, ack only when both copies hold the batch,
  roll the leader tail back when the chain cannot be completed (no
  follower, or the candidate refuses because it holds a leader-role copy —
  `ingester.py` ``persist``/``replica_persist``).
* ``replica_persist`` is modeled as *full convergence* (follower log :=
  leader log): the real protocol sends one batch and heals gaps by
  backfilling from ``gap.have`` (`cluster.py _make_replicate`), which
  converges to the same post-state because every reset position is bounded
  by the registered leader's head (see `docs/model-checking.md`).
* ``wal_fsync(n)`` exposes the fsync boundary explicitly: with
  ``fsync=True`` (production `Ingester` default) every append advances the
  durable watermark atomically; with ``fsync=False`` durability lags until
  an explicit fsync, and ``crash(n)`` truncates the log to the durable
  prefix (power-loss semantics, `wal.py` recovery contract).
* ``promote_replica(n)`` / ``restart(n)`` / ``restart_demote(n)`` mirror
  failover against a durable *chain registry* (the metastore records the
  current ``(leader, follower)`` pair): promotion is only offered to the
  REGISTERED follower — the one node guaranteed to hold the complete
  acked prefix; a replica that crashed and rejoined is stale even though
  its disk looks healthy, and checking at ``crashes=2`` is what exposed
  that a mere per-replica "synced" flag is not a sound eligibility rule.
  A crashed leader that rejoins after its replica was promoted demotes
  its stale leader-role copy on restart (``restart_demote`` = restart +
  ``replica_reset`` at the published checkpoint).  The
  ``stale_rejoin=True`` variant disables the demotion — reproducing the
  defect this model surfaced in the implementation (a rejoined stale
  leader re-uses positions and the checkpoint race loses an acked
  record).
* ``publish_from(n)`` / ``truncate(n)`` / ``replica_truncate(n)`` abstract
  the drain → publish → truncate path to a single shared checkpoint cursor
  (the metastore CAS admits exactly one publisher per position).
* ``break_wal=True`` plants the `QW_DST_BREAK_WAL` bug: the replication
  link drops each batch's tail and swallows the gap report.

Checked properties: **zero-loss failover** (every acked record is
published or still on some disk, dead disks included), **no duplicate
publish**, durable-watermark bounds, **checkpoint monotonicity** and
published-sequence append-onlyness (transition invariants), deadlock
freedom, and the liveness goal that every producer op eventually resolves
under weak fairness of the recovery actions.

**CheckpointModel** — WAL drain → publish → truncate checkpointing, from
`indexing/pipeline.py` + `metastore/checkpoint.py` + `file_backed.py`:

* ``ingest`` appends to the WAL; record == position (sequential ints).
* ``read(i)`` stages a drain from the indexer's cached checkpoint view
  (`pipeline.py run_to_completion` reads the source checkpoint once);
  ``publish(i)`` is the CAS: a delta whose ``from`` matches the metastore
  checkpoint publishes and advances it, a stale delta is rejected
  (`checkpoint.py try_apply_delta` → IncompatibleCheckpointDelta) and the
  staged splits are dropped for the next pass to redo.
* ``poll(i)`` refreshes a stale view (`file_backed.py` polling);
  ``truncate`` reclaims the WAL behind the checkpoint; ``crash(i)`` kills
  a pipeline mid-drain — staged splits are garbage, the restarted pipeline
  re-reads the checkpoint.
* ``break_publish=True`` plants `QW_DST_BREAK_PUBLISH`: drains always
  restart from position zero into a fresh partition and never truncate, so
  the second pass duplicates every record.

Checked properties: **exactly-once publish**, **no loss** (truncated
records must have been published), checkpoint bounds + monotonicity,
published append-onlyness, deadlock freedom, and liveness: all ingested
records are eventually published (weak fairness of ``poll`` is what rules
out the stale-view CAS-retry livelock — remove it and the checker reports
the lasso).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .kernel import Label, Model, State


def _end(node: dict[str, Any]) -> int:
    return node["first"] + len(node["recs"])


class ReplicationModel(Model):
    name = "replication"

    def __init__(self, nodes: int = 3, producers: int = 2, ops: int = 2,
                 crashes: int = 1, fsync: bool = True,
                 break_wal: bool = False, stale_rejoin: bool = False):
        self.n_nodes = nodes
        self.n_producers = producers
        self.ops = ops
        self.crashes = crashes
        self.fsync = fsync
        self.break_wal = break_wal
        self.stale_rejoin = stale_rejoin
        self.config = {
            "nodes": nodes, "producers": producers, "ops": ops,
            "crashes": crashes, "fsync": fsync, "break_wal": break_wal,
            "stale_rejoin": stale_rejoin,
        }
        self.node_ids = [f"n{i}" for i in range(nodes)]
        self.producer_ids = [f"p{i}" for i in range(producers)]

    # ------------------------------------------------------------------
    def initial_state(self) -> State:
        return {
            "nodes": {
                nid: {"alive": True,
                      "role": "leader" if i == 0 else None,
                      "first": 0, "recs": [], "durable": 0}
                for i, nid in enumerate(self.node_ids)},
            # the durable chain registry (metastore shard-leadership
            # records): who leads, and which single replica is the
            # current chain target — the only node promotion may pick
            "leader": self.node_ids[0],
            "follower": None,
            "pending": None,
            "acked": [],
            "remaining": {pid: self.ops for pid in self.producer_ids},
            "next_rec": 0,
            "pub_pos": 0,
            "published": [],
            "crashes_left": self.crashes,
        }

    @staticmethod
    def _copy(s: State) -> State:
        # hand-rolled deep copy of the known state shape: this is the
        # hottest path in the whole checker (one copy per transition)
        return {
            "nodes": {nid: {"alive": n["alive"], "role": n["role"],
                            "first": n["first"],
                            "recs": list(n["recs"]),
                            "durable": n["durable"]}
                      for nid, n in s["nodes"].items()},
            "leader": s["leader"],
            "follower": s["follower"],
            "pending": None if s["pending"] is None else dict(s["pending"]),
            "acked": [list(a) for a in s["acked"]],
            "remaining": dict(s["remaining"]),
            "next_rec": s["next_rec"],
            "pub_pos": s["pub_pos"],
            "published": list(s["published"]),
            "crashes_left": s["crashes_left"],
        }

    # ------------------------------------------------------------------
    def _follower_candidate(self, s: State) -> Optional[str]:
        """First alive non-leader, in node order — `cluster.py
        _follower_for` (which iterates `alive_nodes()` sorted)."""
        for nid in self.node_ids:
            if nid != s["leader"] and s["nodes"][nid]["alive"]:
                return nid
        return None

    def actions(self, s: State) -> list[tuple[Label, State]]:
        out: list[tuple[Label, State]] = []
        leader_id = s["leader"]
        leader = s["nodes"][leader_id]
        pending = s["pending"]

        # persist(p): leader appends the batch inside its critical section
        if pending is None and leader["alive"]:
            for pid in self.producer_ids:
                if s["remaining"][pid] <= 0:
                    continue
                t = self._copy(s)
                tl = t["nodes"][leader_id]
                tl["recs"].append(t["next_rec"])
                if self.fsync:
                    tl["durable"] = _end(tl)
                t["pending"] = {"producer": pid, "rec": t["next_rec"],
                                "stage": "appended"}
                t["next_rec"] += 1
                out.append((f"persist({pid})", t))

        if pending is not None and pending["stage"] == "appended" \
                and leader["alive"]:
            cand = self._follower_candidate(s)
            if cand is not None \
                    and s["nodes"][cand]["role"] != "leader":
                # replica_persist: converge the follower to the leader log
                # (batch + gap backfill), registering it as the chain
                # target first (durably, so promotion after a total outage
                # still picks the right node); under break_wal the link
                # drops the batch tail and swallows the gap report
                t = self._copy(s)
                tl, tf = t["nodes"][leader_id], t["nodes"][cand]
                tf["role"] = "replica"
                tf["first"] = tl["first"]
                tf["recs"] = list(tl["recs"][:-1] if self.break_wal
                                  else tl["recs"])
                tf["durable"] = _end(tf) if self.fsync else tf["first"]
                t["follower"] = cand
                t["pending"]["stage"] = "replicated"
                out.append(("replica_persist", t))
            else:
                # no completable chain (no follower, or the candidate
                # holds a leader-role copy and refuses): NACK + roll the
                # leader tail back (`ingester.py persist` except-path).
                # The tail-match check keeps the action total in the
                # split-brain bug variants, where a stale peer's publishes
                # can truncate the in-flight tail out from under us.
                t = self._copy(s)
                tl = t["nodes"][leader_id]
                if tl["recs"] and tl["recs"][-1] == pending["rec"]:
                    tl["recs"].pop()
                    tl["durable"] = min(tl["durable"], _end(tl))
                t["remaining"][pending["producer"]] -= 1
                t["pending"] = None
                out.append(("rollback", t))

        if pending is not None and pending["stage"] == "replicated":
            # ack: both copies hold the record; the client is answered
            t = self._copy(s)
            t["acked"].append([_end(leader) - 1, pending["rec"]])
            t["remaining"][pending["producer"]] -= 1
            t["pending"] = None
            out.append(("ack", t))

        if not self.fsync:
            for nid in self.node_ids:
                n = s["nodes"][nid]
                if n["alive"] and n["durable"] < _end(n):
                    t = self._copy(s)
                    t["nodes"][nid]["durable"] = _end(t["nodes"][nid])
                    out.append((f"wal_fsync({nid})", t))

        # crash(n): power loss — the log survives truncated to the
        # durable prefix; a leader crash mid-persist errors the client
        if s["crashes_left"] > 0:
            for nid in self.node_ids:
                n = s["nodes"][nid]
                if not n["alive"]:
                    continue
                t = self._copy(s)
                tn = t["nodes"][nid]
                tn["alive"] = False
                tn["recs"] = tn["recs"][:tn["durable"] - tn["first"]]
                if nid == leader_id and t["pending"] is not None:
                    t["remaining"][t["pending"]["producer"]] -= 1
                    t["pending"] = None
                t["crashes_left"] -= 1
                out.append((f"crash({nid})", t))

        for nid in self.node_ids:
            n = s["nodes"][nid]
            if n["alive"]:
                continue
            t = self._copy(s)
            tn = t["nodes"][nid]
            tn["alive"] = True
            if tn["role"] == "leader" and t["leader"] != nid \
                    and not self.stale_rejoin:
                # leadership moved while this node was down: the registry
                # says another node leads, so the recovered leader-role
                # copy demotes itself — replica_reset at the published
                # checkpoint (the durability floor); replica_persist
                # backfill heals it from there
                tn["role"] = "replica"
                tn["first"] = t["pub_pos"]
                tn["recs"] = []
                tn["durable"] = t["pub_pos"]
                out.append((f"restart_demote({nid})", t))
            else:
                out.append((f"restart({nid})", t))

        # promote_replica(n): failover onto the REGISTERED chain follower
        # only — any other replica (e.g. one that crashed and rejoined
        # after the chain moved on) may be missing acked records.  The
        # stale_rejoin (pre-fix) variant promotes any replica, like the
        # implementation did before the chain registry existed.
        if not leader["alive"]:
            if self.stale_rejoin:
                candidates = [nid for nid in self.node_ids
                              if s["nodes"][nid]["alive"]
                              and s["nodes"][nid]["role"] == "replica"]
            else:
                candidates = [s["follower"]] if s["follower"] is not None \
                    else []
            for nid in candidates:
                n = s["nodes"][nid]
                if n["alive"] and n["role"] == "replica":
                    t = self._copy(s)
                    tn = t["nodes"][nid]
                    tn["role"] = "leader"
                    if not self.stale_rejoin and _end(tn) < t["pub_pos"]:
                        # the published checkpoint is past this log's head
                        # (the old leader's recovery-committed tail was
                        # drained): forward-reset so new appends cannot
                        # land on already-consumed positions — everything
                        # dropped is below the checkpoint, hence published
                        tn["first"] = t["pub_pos"]
                        tn["recs"] = []
                        tn["durable"] = t["pub_pos"]
                    t["leader"] = nid
                    t["follower"] = None
                    out.append((f"promote_replica({nid})", t))

        # publish_from(n): drain → publish; the shared checkpoint cursor
        # admits exactly one publisher per position (metastore CAS).  The
        # drain is clamped to the COMMITTED watermark: an in-flight
        # appended-but-unreplicated tail is not publishable (`ingester.py
        # Shard.committed_position` bounds fetch the same way)
        for nid in self.node_ids:
            n = s["nodes"][nid]
            committed = _end(n)
            if nid == leader_id and pending is not None \
                    and pending["stage"] == "appended":
                committed -= 1
            if n["alive"] and n["role"] == "leader" \
                    and n["first"] <= s["pub_pos"] < committed:
                t = self._copy(s)
                tn = t["nodes"][nid]
                t["published"].append(tn["recs"][t["pub_pos"] - tn["first"]])
                t["pub_pos"] += 1
                out.append((f"publish_from({nid})", t))

        # truncate behind the published checkpoint (leader truncate or
        # the propagated replica_truncate)
        for nid in self.node_ids:
            n = s["nodes"][nid]
            if not n["alive"] or n["role"] not in ("leader", "replica"):
                continue
            drop = min(s["pub_pos"], _end(n)) - n["first"]
            if drop <= 0:
                continue
            t = self._copy(s)
            tn = t["nodes"][nid]
            tn["first"] += drop
            tn["recs"] = tn["recs"][drop:]
            tn["durable"] = max(tn["durable"], tn["first"])
            verb = "truncate" if n["role"] == "leader" else "replica_truncate"
            out.append((f"{verb}({nid})", t))

        return out

    # ------------------------------------------------------------------
    def invariants(self) -> list[tuple[str, Callable[[State], bool]]]:
        def zero_loss(s: State) -> bool:
            published = set(s["published"])
            on_disk = {rec for n in s["nodes"].values() for rec in n["recs"]}
            return all(rec in published or rec in on_disk
                       for _pos, rec in s["acked"])

        def no_dup_publish(s: State) -> bool:
            return len(s["published"]) == len(set(s["published"]))

        def durable_bounds(s: State) -> bool:
            return all(n["first"] <= n["durable"] <= _end(n)
                       for n in s["nodes"].values())

        return [("zero_loss", zero_loss),
                ("no_dup_publish", no_dup_publish),
                ("durable_bounds", durable_bounds)]

    def transition_invariants(
            self) -> list[tuple[str, Callable[[State, Label, State], bool]]]:
        return [
            ("checkpoint_monotonic",
             lambda s, _l, t: t["pub_pos"] >= s["pub_pos"]),
            ("published_append_only",
             lambda s, _l, t: t["published"][:len(s["published"])]
             == s["published"]),
        ]

    def is_terminal(self, s: State) -> bool:
        return s["pending"] is None \
            and all(v == 0 for v in s["remaining"].values())

    def liveness_goal(self) -> Optional[Callable[[State], bool]]:
        return self.is_terminal

    def weakly_fair(self, label: Label) -> bool:
        # the recovery/progress actions a supervisor keeps retrying; the
        # chaos actions (crash, fsync timing, publish/truncate pacing)
        # are unconstrained
        return label.split("(")[0] in {
            "persist", "replica_persist", "ack", "rollback", "restart",
            "restart_demote", "promote_replica"}

    def symmetries(self) -> list[dict[str, str]]:
        perms: list[dict[str, str]] = []
        # non-initial-leader nodes are interchangeable, producers too
        node_swaps = [{}]
        if self.n_nodes == 3:
            node_swaps.append({"n1": "n2", "n2": "n1"})
        prod_swaps = [{}]
        if self.n_producers == 2:
            prod_swaps.append({"p0": "p1", "p1": "p0"})
        for ns in node_swaps:
            for ps in prod_swaps:
                if ns or ps:
                    perms.append({**ns, **ps})
        return perms


class CheckpointModel(Model):
    name = "checkpoint"

    def __init__(self, records: int = 3, indexers: int = 2,
                 crashes: int = 1, break_publish: bool = False):
        self.records = records
        self.n_indexers = indexers
        self.crashes = crashes
        self.break_publish = break_publish
        self.config = {"records": records, "indexers": indexers,
                       "crashes": crashes, "break_publish": break_publish}
        self.indexer_ids = [f"i{i}" for i in range(indexers)]

    # ------------------------------------------------------------------
    def initial_state(self) -> State:
        return {
            "first": 0,        # retained-WAL start (truncation watermark)
            "next": 0,         # WAL head; record k lives at position k
            "ckpt": 0,         # metastore source checkpoint (CAS-guarded)
            "published": [],
            "indexers": {iid: {"view": 0, "staged": None}
                         for iid in self.indexer_ids},
            "crashes_left": self.crashes,
        }

    @staticmethod
    def _copy(s: State) -> State:
        return {
            "first": s["first"], "next": s["next"], "ckpt": s["ckpt"],
            "published": list(s["published"]),
            "indexers": {iid: {"view": ix["view"],
                               "staged": None if ix["staged"] is None
                               else dict(ix["staged"])}
                         for iid, ix in s["indexers"].items()},
            "crashes_left": s["crashes_left"],
        }

    def actions(self, s: State) -> list[tuple[Label, State]]:
        out: list[tuple[Label, State]] = []

        if s["next"] < self.records:
            t = self._copy(s)
            t["next"] += 1
            out.append(("ingest", t))

        for iid in self.indexer_ids:
            ix = s["indexers"][iid]

            if ix["view"] != s["ckpt"]:
                # refresh a stale cached checkpoint (metastore polling)
                t = self._copy(s)
                t["indexers"][iid]["view"] = t["ckpt"]
                out.append((f"poll({iid})", t))

            if ix["staged"] is None:
                # read(i): stage a drain from the cached checkpoint view;
                # the planted publish bug always re-reads from zero
                lo = s["first"] if self.break_publish \
                    else max(ix["view"], s["first"])
                if lo < s["next"]:
                    t = self._copy(s)
                    t["indexers"][iid]["staged"] = {"from": lo,
                                                    "to": t["next"]}
                    out.append((f"read({iid})", t))
            else:
                # publish(i): the checkpoint CAS — or, under the planted
                # bug, an unconditional publish into a fresh partition
                t = self._copy(s)
                staged = t["indexers"][iid]["staged"]
                if self.break_publish:
                    t["published"].extend(
                        range(staged["from"], staged["to"]))
                elif staged["from"] == t["ckpt"]:
                    t["published"].extend(
                        range(staged["from"], staged["to"]))
                    t["ckpt"] = staged["to"]
                # else: IncompatibleCheckpointDelta — splits dropped,
                # the next read()/publish() pass redoes the work
                t["indexers"][iid]["staged"] = None
                out.append((f"publish({iid})", t))

                if s["crashes_left"] > 0:
                    # crash(i): pipeline dies mid-drain; staged splits are
                    # garbage-collected, the restart re-reads the metastore
                    t = self._copy(s)
                    t["indexers"][iid]["staged"] = None
                    t["indexers"][iid]["view"] = t["ckpt"]
                    t["crashes_left"] -= 1
                    out.append((f"crash({iid})", t))

        if not self.break_publish and s["first"] < s["ckpt"]:
            t = self._copy(s)
            t["first"] = t["ckpt"]
            out.append(("truncate", t))

        return out

    # ------------------------------------------------------------------
    def invariants(self) -> list[tuple[str, Callable[[State], bool]]]:
        def exactly_once(s: State) -> bool:
            return len(s["published"]) == len(set(s["published"]))

        def no_loss(s: State) -> bool:
            # every truncated record must have been published
            published = set(s["published"])
            return all(r in published for r in range(s["first"]))

        def ckpt_bounds(s: State) -> bool:
            if not (s["first"] <= s["ckpt"] <= s["next"]):
                return False
            for ix in s["indexers"].values():
                if ix["view"] > s["ckpt"]:
                    return False
                if ix["staged"] is not None and not \
                        (0 <= ix["staged"]["from"] <= ix["staged"]["to"]
                         <= s["next"]):
                    return False
            return True

        return [("exactly_once", exactly_once), ("no_loss", no_loss),
                ("ckpt_bounds", ckpt_bounds)]

    def transition_invariants(
            self) -> list[tuple[str, Callable[[State, Label, State], bool]]]:
        return [
            ("checkpoint_monotonic",
             lambda s, _l, t: t["ckpt"] >= s["ckpt"]),
            ("published_append_only",
             lambda s, _l, t: t["published"][:len(s["published"])]
             == s["published"]),
        ]

    def is_terminal(self, s: State) -> bool:
        return s["next"] == self.records and s["ckpt"] == s["next"] \
            and all(ix["staged"] is None for ix in s["indexers"].values())

    def liveness_goal(self) -> Optional[Callable[[State], bool]]:
        return self.is_terminal

    def weakly_fair(self, label: Label) -> bool:
        # poll's fairness is load-bearing: without it the stale-view
        # read → CAS-reject → read livelock is a legitimate lasso
        return label.split("(")[0] in {"ingest", "poll", "read", "publish"}

    def symmetries(self) -> list[dict[str, str]]:
        if self.n_indexers == 2:
            return [{"i0": "i1", "i1": "i0"}]
        return []


# ----------------------------------------------------------------------

MODELS: dict[str, type[Model]] = {
    "replication": ReplicationModel,
    "checkpoint": CheckpointModel,
}


def build_model(name: str, **config: Any) -> Model:
    """Construct a model by name with config overrides — the constructor
    used both by the CLI and by counterexample-artifact replay."""
    if name not in MODELS:
        raise ValueError(
            f"unknown model {name!r} (known: {sorted(MODELS)})")
    return MODELS[name](**config)
