"""qwmc counterexample artifacts — same envelope as DST replay artifacts.

A counterexample artifact is self-contained: the model name + config
rebuild the exact model via `models.build_model`, and the recorded label
path (plus lasso cycle, for liveness) re-executes deterministically with
`kernel.replay_path`.  The envelope (single ``version`` field, ``kind``,
blake2b integrity ``digest``) comes from `quickwit_tpu/dst/artifact.py` —
one schema for both artifact families, so `dst replay` and `qwmc replay`
formats cannot drift apart.
"""

from __future__ import annotations

import os
from typing import Any

from quickwit_tpu.dst.artifact import (QWMC_KIND, finish_artifact,
                                       load_artifact, save_artifact)
from quickwit_tpu.dst.trace import blake2b_digest

from .kernel import CheckResult, replay_path
from .models import build_model


def make_counterexample_artifact(result: CheckResult) -> dict[str, Any]:
    if result.violation is None:
        raise ValueError("no violation to persist")
    return finish_artifact(QWMC_KIND, {
        "model": result.model,
        "config": dict(result.config),
        "explored": {"states": result.states,
                     "transitions": result.transitions,
                     "depth": result.depth,
                     "complete": result.complete},
        "violation": result.violation.to_dict(),
    })


def artifact_path(artifacts_dir: str, artifact: dict[str, Any]) -> str:
    return os.path.join(
        artifacts_dir,
        f"qwmc-{artifact['model']}-{artifact['digest'][:12]}.json")


def save_counterexample(result: CheckResult, artifacts_dir: str) -> str:
    artifact = make_counterexample_artifact(result)
    os.makedirs(artifacts_dir, exist_ok=True)
    path = artifact_path(artifacts_dir, artifact)
    save_artifact(artifact, path, kind=QWMC_KIND)
    return path


def replay_artifact(path: str) -> dict[str, Any]:
    """Re-execute a counterexample artifact from its contents alone.

    Rebuilds the model from the recorded config, replays the label path
    (and one lasso revolution, for liveness counterexamples), and checks
    the reached state is byte-identical to the recorded violating state —
    the qwmc analogue of `dst replay`'s trace-digest comparison.  Returns
    a verdict dict; ``reproduced`` is True on an exact match.
    """
    artifact = load_artifact(path, kind=QWMC_KIND)
    violation = artifact["violation"]
    model = build_model(artifact["model"], **artifact["config"])
    cycle = violation.get("cycle") or None
    final = replay_path(model, violation["path"], cycle)
    if cycle:
        # liveness: the recorded state is the lasso entry; replay the stem
        # alone to compare, then the full stem+cycle above proves the
        # cycle's actions stay enabled
        final = replay_path(model, violation["path"])
    reproduced = blake2b_digest(final) == blake2b_digest(violation["state"])
    return {
        "artifact": path,
        "model": artifact["model"],
        "kind": violation["kind"],
        "name": violation["name"],
        "steps": len(violation["path"]),
        "reproduced": reproduced,
    }
