"""qwmc: explicit-state model checker for the quickwit_tpu protocols.

Stdlib-only, mirroring qwlint's shape: a small kernel (`kernel.py`), the
protocol models extracted from the implementation (`models.py`), canonical
counterexample artifacts sharing the DST schema (`artifact.py`), the
DST-trace refinement bridge (`conformance.py`), and a CLI (`__main__.py`)
with qwlint-style exit codes (0 = verified, 1 = violation found,
2 = usage/internal error).

The DST harness (`quickwit_tpu/dst/`) explores *seeds*; qwmc explores the
*full reachable state space* of the two protocols the DST exercises —
chained replication (ingester WAL + replica chain) and WAL-drain →
publish → truncate checkpointing — exhaustively to a pinned bound.  The
conformance bridge closes the loop: every DST trace must be a behavior of
the abstract model, so the models cannot silently drift from the code.
"""

from .kernel import CheckResult, Model, ModelViolation, check_model, replay_path

__all__ = [
    "CheckResult",
    "Model",
    "ModelViolation",
    "check_model",
    "replay_path",
]
