"""DST-trace → checkpoint-model refinement bridge.

`check_trace` replays a deterministic-simulation trace (the event list a
`quickwit_tpu.dst` run records) against the abstract transition relation
of `models.CheckpointModel`, per index:

    concrete event                    abstract action / guard
    ------------------------------    --------------------------------
    ingest acked n docs               n × `ingest`: next += n
    drain published k docs            `read`+`publish`: requires
                                      published + k <= next — publishing
                                      more records than were ever acked
                                      into the WAL is not a behavior of
                                      the model (its publish CAS consumes
                                      each position exactly once)
    drain-reported checkpoint total   the model's `ckpt` counter
    quiescence                        `is_terminal`: ckpt == next — weak
                                      fairness of poll/read/publish makes
                                      the model converge, so a run whose
                                      final checkpoint is short of the
                                      acked count lost records

A violation is reported under the MODEL's invariant name (`exactly_once`
for the publish-guard failure, `zero_loss` for the convergence failure),
tying a non-conforming trace directly to the counterexample the planted
bugs (`QW_DST_BREAK_PUBLISH`, `QW_DST_BREAK_WAL`) produce under
`python -m tools.qwmc check checkpoint` / `replication`.

Pure function of the trace: no cluster, no clock, no I/O — callable from
the sweep loop (`dst sweep --conformance`) and from tests alike.
"""

from __future__ import annotations

from typing import Any, Optional


class _AbstractIndex:
    """The refinement image of one index: the checkpoint model's counters
    with the per-indexer structure abstracted away (the trace only shows
    committed effects, which is exactly the quotient the guards need)."""

    def __init__(self) -> None:
        self.acked = 0        # model `next`: acked WAL appends
        self.published = 0    # model `len(published)`: records in splits
        self.ckpt: Optional[int] = None  # model `ckpt`: last observed

    def ingest(self, n: int) -> None:
        self.acked += n

    def observe_ckpt(self, ckpt: Optional[int]) -> None:
        # max-merge: a node's polling cache may report an already-superseded
        # checkpoint (staleness, not a protocol violation) — the model's
        # `ckpt` is the monotone envelope of the observations
        if ckpt is not None:
            self.ckpt = max(self.ckpt or 0, int(ckpt))

    def publish(self, indexed: int, ckpt: Optional[int]) -> Optional[str]:
        self.published += indexed
        self.observe_ckpt(ckpt)
        if self.published > self.acked:
            return (f"published {self.published} records but only "
                    f"{self.acked} were ever acked — re-publication of "
                    "consumed WAL positions (model invariant exactly_once)")
        return None

    def finalize(self) -> Optional[str]:
        ckpt = self.ckpt if self.ckpt is not None else 0
        if ckpt < self.acked:
            return (f"quiesced with checkpoint {ckpt} short of "
                    f"{self.acked} acked records — the model's fair "
                    "drain/publish loop converges to ckpt == next, so the "
                    "gap is lost data (model invariant zero_loss)")
        if ckpt > self.acked:
            return (f"quiesced with checkpoint {ckpt} beyond the "
                    f"{self.acked} acked records — positions were "
                    "published that no ack ever covered (model invariant "
                    "exactly_once)")
        return None


def _drain_results(event: dict[str, Any]):
    """Yield (index_id, per-index drain dict) pairs from an `op` event
    with a drain result or from each drain in a `quiesce` summary."""
    if event["kind"] == "op" and event.get("op", {}).get("kind") == "drain":
        result = event.get("result")
        if isinstance(result, dict):
            yield from ((idx, r) for idx, r in result.items()
                        if isinstance(r, dict))
    elif event["kind"] == "quiesce":
        for key, drain in event.get("summary", {}).items():
            if key.startswith("drain") and isinstance(drain, dict):
                yield from ((idx, r) for idx, r in drain.items()
                            if isinstance(r, dict))


def check_trace(events: list[dict[str, Any]]) -> dict[str, Any]:
    """Replay `events` through the abstract checkpoint machine. Returns a
    JSON-safe report: `conforms`, the per-index final counters, and one
    entry per guard violation (model-invariant name, index, step)."""
    indexes: dict[str, _AbstractIndex] = {}
    violations: list[dict[str, Any]] = []

    def index_of(index_id: str) -> _AbstractIndex:
        return indexes.setdefault(index_id, _AbstractIndex())

    quiesced = False
    for event in events:
        step = event.get("step")
        if event["kind"] == "op" and \
                event.get("op", {}).get("kind") == "ingest":
            result = event.get("result")
            if isinstance(result, dict) and "acked" in result:
                index_of(event["op"]["index"]).ingest(int(result["acked"]))
            continue
        for index_id, drain in _drain_results(event):
            if "indexed" not in drain:
                # skipped / errored drain: no publish action, but a
                # checkpoint reading (if any) is still an observation
                index_of(index_id).observe_ckpt(drain.get("checkpoint"))
                continue
            error = index_of(index_id).publish(int(drain["indexed"]),
                                               drain.get("checkpoint"))
            if error is not None:
                violations.append({"invariant": "exactly_once",
                                   "index": index_id, "step": step,
                                   "detail": error})
        if event["kind"] == "quiesce":
            quiesced = True

    # final-state guard only when the run actually converged: a run cut
    # short by an invariant violation never drained its tail, and flagging
    # that as loss would double-report the primary failure
    if quiesced:
        for index_id, abstract in sorted(indexes.items()):
            error = abstract.finalize()
            if error is not None:
                name = ("zero_loss" if (abstract.ckpt or 0) < abstract.acked
                        else "exactly_once")
                violations.append({"invariant": name, "index": index_id,
                                   "step": None, "detail": error})

    return {
        "conforms": not violations,
        "quiesced": quiesced,
        "indexes": {idx: {"acked": a.acked, "published": a.published,
                          "checkpoint": a.ckpt}
                    for idx, a in sorted(indexes.items())},
        "violations": violations,
    }
