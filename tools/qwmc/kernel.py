"""Explicit-state model-checker kernel.

States are plain JSON-shaped Python structures (dicts/lists/tuples/
scalars).  A model contributes an initial state, a set of guarded actions
(`actions(state)` returns the enabled `(label, successor)` pairs), safety
invariants evaluated on every reachable state, transition invariants
evaluated on every explored edge, and optionally a liveness goal with
weakly-fair action labels.  The kernel does the rest:

* **BFS** over the reachable state space with canonical-form hashing —
  every state is frozen into a hashable canonical form before dedup, so
  models can return ordinary mutable structures.
* **Symmetry reduction**: a model may declare id-renaming symmetries
  (permutations of node/replica/producer ids); the canonical form of a
  state is the minimum frozen form over the whole permutation group, which
  collapses symmetric states into one representative.  The parent map
  stores *concrete* states, so every counterexample path is a genuine
  execution of the model, never a permuted collage.
* **Safety counterexamples** are minimal by construction: BFS reaches every
  state along a shortest label path, so the first violation found is
  already shrunk to the fewest possible steps.
* **Deadlock detection**: a state with no enabled action that the model
  does not declare terminal is reported with its (shortest) path.
* **Weak-fairness lasso detection** for liveness (`<> goal`): after the
  full state graph is built, Tarjan SCCs of the subgraph induced on
  non-goal states are tested.  An SCC admits a weakly-fair lasso iff every
  weakly-fair action label enabled in *every* state of the SCC has an edge
  inside the SCC — exactness holds because the witness cycle constructed
  below visits every SCC state, so its continuously-enabled label set is
  precisely the SCC-wide one.

Stdlib only; no imports from quickwit_tpu (the artifact layer bridges the
two worlds, see `artifact.py`).
"""

from __future__ import annotations

import functools
import json
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional

State = Any
Label = str

_dumps = functools.partial(json.dumps, sort_keys=True,
                           separators=(",", ":"))


# ----------------------------------------------------------------------
# canonical frozen forms


def freeze(value: State) -> Any:
    """Recursively convert a JSON-shaped structure into a hashable
    canonical form (dicts become sorted key/value tuples)."""
    if isinstance(value, dict):
        return ("d",) + tuple(
            (k, freeze(v)) for k, v in sorted(value.items()))
    if isinstance(value, (list, tuple)):
        return ("l",) + tuple(freeze(v) for v in value)
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    raise TypeError(f"unfreezable state component: {type(value).__name__}")


def rename(value: State, mapping: dict[str, str]) -> State:
    """Apply an id-renaming symmetry: every string (key or value) that is
    exactly a mapped id is replaced.  Substrings are never touched."""
    if isinstance(value, dict):
        return {mapping.get(k, k) if isinstance(k, str) else k:
                rename(v, mapping) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [rename(v, mapping) for v in value]
    if isinstance(value, str):
        return mapping.get(value, value)
    return value


# ----------------------------------------------------------------------
# model protocol


class Model:
    """Base class for checkable models.  Subclasses override the hooks;
    every hook must be deterministic and must not mutate its input state
    (return fresh structures from `actions`)."""

    name = "model"

    #: config dict recorded into artifacts; must rebuild the model via
    #: `build_model(name, **config)` for counterexample replay
    config: dict[str, Any] = {}

    def initial_state(self) -> State:
        raise NotImplementedError

    def actions(self, state: State) -> list[tuple[Label, State]]:
        """All enabled actions as (label, successor) pairs.  Labels must be
        unique within one state (include parameters, e.g. ``crash(n1)``) so
        counterexample paths replay deterministically."""
        raise NotImplementedError

    def invariants(self) -> list[tuple[str, Callable[[State], bool]]]:
        return []

    def transition_invariants(
            self) -> list[tuple[str, Callable[[State, Label, State], bool]]]:
        return []

    def is_terminal(self, state: State) -> bool:
        """True if it is acceptable for this state to have no enabled
        actions (otherwise a successor-less state is a deadlock)."""
        return False

    def symmetries(self) -> list[dict[str, str]]:
        """Id-renaming permutations (excluding identity is fine; the
        kernel always includes it)."""
        return []

    def liveness_goal(self) -> Optional[Callable[[State], bool]]:
        """Predicate for the liveness property ``<> goal``, or None to
        skip liveness checking."""
        return None

    def weakly_fair(self, label: Label) -> bool:
        """Whether an action label is weakly fair (cannot stay enabled
        forever without firing)."""
        return False


# ----------------------------------------------------------------------
# results


@dataclass
class ModelViolation:
    """A property violation with its minimal witness.

    ``kind`` is one of ``invariant`` / ``transition_invariant`` /
    ``deadlock`` / ``liveness``.  ``path`` is the shortest label sequence
    from the initial state to the violating state (for liveness: to the
    lasso entry), and ``cycle`` (liveness only) is the label sequence of a
    weakly-fair cycle that never reaches the goal.  ``state`` is the
    concrete violating state — a genuine execution endpoint, valid for
    deterministic replay via `replay_path`.
    """

    kind: str
    name: str
    path: list[Label]
    state: State
    cycle: list[Label] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        out = {"kind": self.kind, "name": self.name,
               "path": list(self.path), "state": self.state}
        if self.cycle:
            out["cycle"] = list(self.cycle)
        return out


@dataclass
class CheckResult:
    model: str
    config: dict[str, Any]
    states: int
    transitions: int
    depth: int
    violation: Optional[ModelViolation]
    complete: bool  # False when a depth bound cut exploration short

    @property
    def ok(self) -> bool:
        return self.violation is None

    def to_dict(self) -> dict[str, Any]:
        return {
            "model": self.model,
            "config": dict(self.config),
            "states": self.states,
            "transitions": self.transitions,
            "depth": self.depth,
            "complete": self.complete,
            "ok": self.ok,
            "violation": None if self.violation is None
            else self.violation.to_dict(),
        }


# ----------------------------------------------------------------------
# checking


class _Space:
    """Explored state space: canonical key -> bookkeeping."""

    def __init__(self) -> None:
        # key -> (parent_key | None, label | None, concrete_state, depth)
        self.nodes: dict[Any, tuple[Any, Optional[Label], State, int]] = {}
        # key -> list of (label, succ_key); filled during BFS, used by the
        # liveness pass
        self.edges: dict[Any, list[tuple[Label, Any]]] = {}

    def path_to(self, key: Any) -> list[Label]:
        labels: list[Label] = []
        while True:
            parent, label, _state, _depth = self.nodes[key]
            if parent is None:
                break
            labels.append(label)  # type: ignore[arg-type]
            key = parent
        labels.reverse()
        return labels


def _canonicalize(state: State, perms: list[dict[str, str]]) -> Any:
    """Canonical hashable key: sorted-key JSON, minimized over the
    symmetry group.  JSON strings give a C-speed total order (a renaming
    permutes sibling subtrees, so structural tuple comparison could face
    mixed types and raise)."""
    key = _dumps(state)
    for perm in perms:
        candidate = _dumps(rename(state, perm))
        if candidate < key:
            key = candidate
    return key


def check_model(model: Model, depth: Optional[int] = None,
                symmetry: bool = True,
                max_states: int = 2_000_000) -> CheckResult:
    """Exhaustively explore `model` (optionally to a BFS depth bound) and
    return the first violation found, if any.  Safety violations are
    reported as soon as a violating state/edge is *generated* (so their
    paths are shortest); deadlock and liveness are judged on the explored
    graph afterwards."""
    perms = [p for p in model.symmetries() if p] if symmetry else []
    invariants = model.invariants()
    transition_invariants = model.transition_invariants()

    # memoized canonicalization: distinct parents regenerate the same
    # concrete successor often, and the symmetry minimization (rename +
    # dumps per permutation) is the hottest part of the whole search
    canon_cache: dict[str, str] = {}

    def canon(state: State) -> str:
        base = _dumps(state)
        key = canon_cache.get(base)
        if key is None:
            if perms:
                key = base
                for perm in perms:
                    candidate = _dumps(rename(state, perm))
                    if candidate < key:
                        key = candidate
            else:
                key = base
            canon_cache[base] = key
        return key

    space = _Space()
    init = model.initial_state()
    init_key = canon(init)
    space.nodes[init_key] = (None, None, init, 0)

    for name, pred in invariants:
        if not pred(init):
            return CheckResult(
                model.name, model.config, 1, 0, 0,
                ModelViolation("invariant", name, [], init), True)

    queue: deque[Any] = deque([init_key])
    transitions = 0
    max_depth_seen = 0
    complete = True

    while queue:
        key = queue.popleft()
        _parent, _label, state, d = space.nodes[key]
        if depth is not None and d >= depth:
            complete = False
            continue
        enabled = model.actions(state)
        out_edges: list[tuple[Label, Any]] = []
        if not enabled and not model.is_terminal(state):
            return CheckResult(
                model.name, model.config, len(space.nodes), transitions, d,
                ModelViolation("deadlock", "no enabled actions",
                               space.path_to(key), state), complete)
        seen_labels: set[Label] = set()
        for label, succ in enabled:
            if label in seen_labels:
                raise ValueError(
                    f"{model.name}: duplicate action label {label!r} in one "
                    f"state — labels must be unique for replay")
            seen_labels.add(label)
            transitions += 1
            for name, tpred in transition_invariants:
                if not tpred(state, label, succ):
                    return CheckResult(
                        model.name, model.config, len(space.nodes),
                        transitions, d + 1,
                        ModelViolation("transition_invariant", name,
                                       space.path_to(key) + [label], succ),
                        complete)
            for name, pred in invariants:
                if not pred(succ):
                    return CheckResult(
                        model.name, model.config, len(space.nodes),
                        transitions, d + 1,
                        ModelViolation("invariant", name,
                                       space.path_to(key) + [label], succ),
                        complete)
            succ_key = canon(succ)
            out_edges.append((label, succ_key))
            if succ_key not in space.nodes:
                if len(space.nodes) >= max_states:
                    raise RuntimeError(
                        f"{model.name}: state-space explosion "
                        f"(> {max_states} states) — tighten the bound")
                space.nodes[succ_key] = (key, label, succ, d + 1)
                max_depth_seen = max(max_depth_seen, d + 1)
                queue.append(succ_key)
        space.edges[key] = out_edges

    violation = None
    goal = model.liveness_goal()
    if goal is not None and complete:
        violation = _find_fair_lasso(model, space, goal, perms)
        if violation is not None and perms:
            # Candidate only: in the symmetry quotient, parametrized labels
            # from different orbit representatives mix inside one SCC, which
            # can only SHRINK the always-enabled fair-label intersection —
            # the quotient test over-approximates lassos (never misses one).
            # Confirm on the unreduced graph, where the test is exact.
            full = _explore_plain(model, max_states)
            violation = _find_fair_lasso(model, full, goal, [])
    return CheckResult(model.name, model.config, len(space.nodes),
                       transitions, max_depth_seen, violation, complete)


def _explore_plain(model: Model, max_states: int) -> _Space:
    """Bare reachability BFS without symmetry reduction or property
    checks — builds the exact state graph for the liveness confirm pass."""
    space = _Space()
    init = model.initial_state()
    init_key = _dumps(init)
    space.nodes[init_key] = (None, None, init, 0)
    queue: deque[Any] = deque([init_key])
    while queue:
        key = queue.popleft()
        _p, _l, state, d = space.nodes[key]
        out_edges: list[tuple[Label, Any]] = []
        for label, succ in model.actions(state):
            succ_key = _dumps(succ)
            out_edges.append((label, succ_key))
            if succ_key not in space.nodes:
                if len(space.nodes) >= max_states:
                    raise RuntimeError(
                        f"{model.name}: state-space explosion in liveness "
                        f"confirm pass (> {max_states} states)")
                space.nodes[succ_key] = (key, label, succ, d + 1)
                queue.append(succ_key)
        space.edges[key] = out_edges
    return space


# ----------------------------------------------------------------------
# liveness: weak-fairness lasso detection


def _tarjan_sccs(nodes: set[Any],
                 edges: dict[Any, list[tuple[Label, Any]]]
                 ) -> Iterator[list[Any]]:
    """Iterative Tarjan over the subgraph induced on `nodes`."""
    index: dict[Any, int] = {}
    low: dict[Any, int] = {}
    on_stack: set[Any] = set()
    stack: list[Any] = []
    counter = 0
    for root in nodes:
        if root in index:
            continue
        work = [(root, iter([k for _l, k in edges.get(root, [])
                             if k in nodes]))]
        index[root] = low[root] = counter
        counter += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = low[succ] = counter
                    counter += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append(
                        (succ, iter([k for _l, k in edges.get(succ, [])
                                     if k in nodes])))
                    advanced = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    member = stack.pop()
                    on_stack.discard(member)
                    scc.append(member)
                    if member == node:
                        break
                yield scc


def _internal_path(start: Any, goal_key: Any, members: set[Any],
                   edges: dict[Any, list[tuple[Label, Any]]]
                   ) -> list[tuple[Label, Any]]:
    """Shortest (label, key) path from start to goal within `members`.
    Returns [] when start == goal_key."""
    if start == goal_key:
        return []
    parents: dict[Any, tuple[Any, Label]] = {}
    frontier = deque([start])
    seen = {start}
    while frontier:
        node = frontier.popleft()
        for label, succ in edges.get(node, []):
            if succ not in members or succ in seen:
                continue
            parents[succ] = (node, label)
            if succ == goal_key:
                path: list[tuple[Label, Any]] = []
                cur = succ
                while cur != start:
                    prev, lab = parents[cur]
                    path.append((lab, cur))
                    cur = prev
                path.reverse()
                return path
            seen.add(succ)
            frontier.append(succ)
    raise AssertionError("SCC not strongly connected")  # pragma: no cover


def _find_fair_lasso(model: Model, space: _Space,
                     goal: Callable[[State], bool],
                     perms: list[dict[str, str]]
                     ) -> Optional[ModelViolation]:
    """Search for a reachable weakly-fair cycle among non-goal states.

    The SCC-level test is exact for action-label weak fairness: let E be
    the set of weakly-fair labels enabled in EVERY state of the SCC.  Any
    lasso inside the SCC has all of E continuously enabled, so a fair
    lasso must fire each of them — impossible if some label in E has no
    edge inside the SCC.  Conversely, when every label in E has an
    internal edge, the witness constructed below visits every SCC state
    (so its continuously-enabled set is exactly E) and takes one edge per
    label in E, hence it is weakly fair.
    """
    non_goal = {k for k, (_p, _l, state, _d) in space.nodes.items()
                if not goal(state)}
    for scc in _tarjan_sccs(non_goal, space.edges):
        members = set(scc)
        internal = [(k, label, succ) for k in scc
                    for label, succ in space.edges.get(k, [])
                    if succ in members]
        if not internal:
            continue  # trivial SCC without self-loop: no lasso
        # labels of weakly-fair actions enabled in EVERY member state
        always_enabled: Optional[set[Label]] = None
        for k in scc:
            labels = {label for label, _succ in space.edges.get(k, [])
                      if model.weakly_fair(label)}
            always_enabled = labels if always_enabled is None \
                else always_enabled & labels
            if not always_enabled:
                break
        required = always_enabled or set()
        internal_labels = {label for _k, label, _s in internal}
        if not required <= internal_labels:
            continue  # some fair action can never fire inside: no fair lasso
        # Build the witness cycle: start anywhere, visit every member state
        # (pins the continuously-enabled set to E), take one edge for each
        # required label, and return to the start.
        start = scc[0]
        cycle_edges: list[tuple[Label, Any]] = []
        cur = start
        pending_states = [k for k in scc if k != start]
        pending_labels = dict()
        for lab in required:
            for k, label, succ in internal:
                if label == lab:
                    pending_labels[lab] = (k, label, succ)
                    break
        for target in pending_states:
            seg = _internal_path(cur, target, members, space.edges)
            cycle_edges.extend(seg)
            cur = target
        for k, label, succ in pending_labels.values():
            cycle_edges.extend(_internal_path(cur, k, members, space.edges))
            cycle_edges.append((label, succ))
            cur = succ
        cycle_edges.extend(_internal_path(cur, start, members, space.edges))
        if not cycle_edges:  # single state, required empty, has self-loop
            for k, label, succ in internal:
                if succ == start and k == start:
                    cycle_edges = [(label, succ)]
                    break
        entry_state = space.nodes[start][2]
        # Lift the quotient cycle to concrete labels from the entry state:
        # with symmetry reduction on, stored edge labels are relative to
        # each node's stored representative and may not replay verbatim
        # from the entry state.  One concrete revolution suffices as a
        # witness (the infinite lasso closes after at most |perm group|
        # revolutions, fair by symmetry).
        concrete = entry_state
        lifted: list[Label] = []
        for _label, next_key in cycle_edges:
            for lab, succ in model.actions(concrete):
                if _canonicalize(succ, perms) == next_key:
                    lifted.append(lab)
                    concrete = succ
                    break
            else:  # pragma: no cover - quotient edges always lift
                raise AssertionError("failed to lift lasso cycle")
        return ModelViolation(
            "liveness", "weakly-fair lasso never reaches goal",
            space.path_to(start), entry_state, cycle=lifted)
    return None


# ----------------------------------------------------------------------
# replay


def replay_path(model: Model, labels: list[Label],
                cycle: Optional[list[Label]] = None) -> State:
    """Deterministically re-execute a counterexample path from the initial
    state, raising if any label is not enabled — the determinism oracle
    for qwmc artifacts (mirrors `dst replay`).  When `cycle` is given the
    lasso is replayed once around after the stem."""
    state = model.initial_state()
    for label in list(labels) + list(cycle or []):
        enabled = dict(model.actions(state))
        if label not in enabled:
            raise ValueError(
                f"replay diverged: action {label!r} not enabled "
                f"(enabled: {sorted(enabled)})")
        state = enabled[label]
    return state
