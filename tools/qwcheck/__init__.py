"""qwcheck — the one-command static gate.

Runs every repo-grown analysis in-process and merges the verdicts:

    qwlint  source-level lint of the hot path (tools/qwlint)
    qwmc    exhaustive protocol model checking (tools/qwmc)
    qwir    jaxpr-level audit of the lowered leaf programs + the
            compile-cache closure certificate (tools/qwir)

`python -m tools.qwcheck` exits 0 only when all three are clean; `--json`
emits one merged document `{"qwlint": ..., "qwmc": ..., "qwir": ...,
"ok": ...}` for CI. Individual tools remain runnable on their own; this
package contains no analysis logic of its own.
"""

from __future__ import annotations
