"""CLI: `python -m tools.qwcheck [--json] [--skip TOOL ...]`.

Exit codes: 0 all gates clean, 1 any gate found something, 2 a gate
crashed or was misused. Each gate runs in-process (no subprocesses) so
one `pytest`-free command gives the full static verdict; `--skip` exists
for bisecting which gate is failing, not for shipping around one.
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback

_GATES = ("qwlint", "qwmc", "qwir", "qwrace")


def _run_qwlint() -> tuple[int, dict]:
    from tools.qwlint.core import (analyze_paths, apply_baseline,
                                   default_baseline_path, load_baseline)
    findings = analyze_paths(["quickwit_tpu"])
    entries = load_baseline(default_baseline_path())
    new, stale = apply_baseline(findings, entries)
    return (1 if new else 0), {
        "ok": not new,
        "findings": [f.to_dict() for f in new],
        "baselined": len(findings) - len(new),
        "stale_baseline_entries": len(stale),
    }


def _run_qwmc() -> tuple[int, dict]:
    from tools.qwmc.kernel import check_model
    from tools.qwmc.models import MODELS, build_model
    results = [check_model(build_model(name)) for name in sorted(MODELS)]
    ok = all(r.ok for r in results)
    return (0 if ok else 1), {
        "ok": ok,
        "results": [r.to_dict() for r in results],
    }


def _run_qwir() -> tuple[int, dict]:
    from tools.qwir.__main__ import _setup_platform
    _setup_platform()
    from tools.qwir.audit import run_audit
    from tools.qwir.selftest import run_self_test
    report = run_audit()
    self_test_failures = run_self_test()
    ok = report.ok and not self_test_failures
    doc = report.to_json()
    doc["self_test_failures"] = self_test_failures
    doc["ok"] = ok
    doc.pop("programs", None)  # bulky; the manifest carries the detail
    return (0 if ok else 1), doc


def _run_qwrace() -> tuple[int, dict]:
    from tools.qwrace.__main__ import run_gate
    return run_gate()


_RUNNERS = {"qwlint": _run_qwlint, "qwmc": _run_qwmc, "qwir": _run_qwir,
            "qwrace": _run_qwrace}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.qwcheck",
        description="run qwlint + qwmc + qwir + qwrace as one merged "
                    "gate")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit one merged JSON document")
    parser.add_argument("--skip", action="append", default=[],
                        choices=_GATES, metavar="TOOL",
                        help="skip a gate (repeatable; for bisecting)")
    args = parser.parse_args(argv)

    merged: dict = {}
    worst = 0
    for gate in _GATES:
        if gate in args.skip:
            merged[gate] = {"ok": True, "skipped": True}
            continue
        try:
            rc, doc = _RUNNERS[gate]()
        except Exception as exc:  # a crashed gate is a usage-level failure
            traceback.print_exc()
            print(f"qwcheck: {gate} crashed: {exc}", file=sys.stderr)
            merged[gate] = {"ok": False, "error": str(exc)}
            worst = max(worst, 2)
            continue
        merged[gate] = doc
        worst = max(worst, rc)
        if not args.as_json:
            verdict = "ok" if rc == 0 else "FAIL"
            print(f"qwcheck: {gate}: {verdict}")
    merged["ok"] = worst == 0
    if args.as_json:
        json.dump(merged, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    elif merged["ok"]:
        print("qwcheck: all gates clean")
    return worst


if __name__ == "__main__":
    sys.exit(main())
