"""Planted-defect self-test: proves each rule still catches its bug class.

A static auditor that silently stops finding things is worse than none —
this module builds toy programs each containing exactly one planted
defect (an f64 upcast feeding a corpus-scale top_k, a mid-kernel host
callback, an unbounded padding-bucket enumeration breaking cache closure,
a collective over an undeclared mesh axis, an HBM liveness blowup) and
asserts the matching rule reports exactly that finding, with a stable id.
Run via `python -m tools.qwir self-test`; the fixture suite
(tests/test_qwir_rules.py) drives the same functions per rule.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from . import ir
from .audit import check_closure, manifest_from_programs
from .rules import (
    check_collectives, check_f64, check_hbm, check_transfers,
)


@dataclass
class ToySpec:
    name: str
    closed: Any
    doc_lanes: int = 1024
    num_docs_padded: int = 1024
    mesh_axes: tuple = ("splits", "docs")
    kind: str = "toy"
    cache_key: tuple = ()
    peak: Any = None

    @property
    def cache_key_digest(self) -> str:
        import hashlib
        return hashlib.blake2b(repr(self.cache_key).encode(),
                               digest_size=16).hexdigest()

    def __post_init__(self):
        if self.peak is None:
            self.peak = ir.liveness_peak(self.closed)


def _trace(fn, *shapes):
    import quickwit_tpu  # noqa: F401 — enables x64, matching production tracing
    import jax
    args = [jax.ShapeDtypeStruct(s, d) for s, d in shapes]
    return jax.make_jaxpr(fn)(*args)


# --- planted defects ---------------------------------------------------------

def planted_f64_upcast() -> ToySpec:
    """An innocent-looking f32 score lane promoted to f64 and full-sorted
    at corpus scale — the exact shape of the PR 8 ~290ms top_k bug."""
    import jax
    import jax.numpy as jnp

    def leaf(scores):
        keys = scores.astype(jnp.float64)      # doc-scale f64 promotion
        return jax.lax.top_k(keys, 10)         # f64-keyed corpus-scale sort

    return ToySpec(name="planted/f64_upcast",
                   closed=_trace(leaf, ((16384,), np.float32)),
                   doc_lanes=16384, num_docs_padded=16384)


def planted_host_round_trip() -> ToySpec:
    """A mid-kernel host callback — the traced analogue of calling
    jax.device_get inside the fused dispatch (which cannot trace at all);
    any callback primitive is the same per-query host sync."""
    import jax
    import jax.numpy as jnp

    def leaf(mask):
        count = jax.pure_callback(
            lambda m: np.asarray(m.sum(), dtype=np.int64),
            jax.ShapeDtypeStruct((), np.int64), mask)
        return count + jnp.int64(1)

    return ToySpec(name="planted/host_round_trip",
                   closed=_trace(leaf, ((1024,), np.bool_)))


def planted_bad_collective() -> ToySpec:
    """A psum over a mesh axis the program never declared: the spec says
    the merge runs over ("splits",) only, but the body reduces over
    "docs" — silently wrong replica groups on a real 2D mesh."""
    import jax
    import numpy as np_
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    devs = np_.asarray(jax.devices()[:2]).reshape(1, 2)
    mesh = Mesh(devs, ("splits", "docs"))

    def merge(x):
        return jax.lax.psum(x, "docs")

    fn = shard_map(merge, mesh=mesh, in_specs=P(None, "docs"),
                   out_specs=P(None, None))
    return ToySpec(name="planted/bad_collective",
                   closed=_trace(fn, ((4, 2), np.float32)),
                   mesh_axes=("splits",))


def planted_mesh_axis_leak() -> ToySpec:
    """An undeclared-axis psum through the PRODUCTION collective program
    shape: `fanout.mesh_batch_fn` traced over a mesh whose split axis is
    misnamed ("rows", "docs"). Every collective in the lowered root merge
    — the pmax threshold exchange, the all_gather candidate exchange, the
    psum agg/count reductions — then binds "rows", which the ProgramSpec
    never declared. Catching this through the real builder (not a toy
    body) is what keeps R4 load-bearing for the mesh root-merge programs
    the corpus now pins."""
    import jax
    import numpy as np_
    from jax.sharding import Mesh

    import quickwit_tpu  # noqa: F401 — enables x64, matching production
    from quickwit_tpu.parallel import fanout
    from quickwit_tpu.query.ast import Term
    from quickwit_tpu.search import SearchRequest

    from .corpus import _build_reader, _docs, _mapper

    mapper = _mapper()
    readers = [_build_reader(mapper, _docs(64, seed=11), f"r4mesh{i}.split")
               for i in range(2)]
    request = SearchRequest(index_ids=["t"],
                            query_ast=Term("body", "alpha"), max_hits=5)
    batch = fanout.build_batch(request, mapper, readers, ["a", "b"])
    bad_mesh = Mesh(np_.asarray(jax.devices()[:2]).reshape(2, 1),
                    ("rows", "docs"))
    return ToySpec(name="planted/mesh_axis_leak",
                   closed=fanout.abstract_mesh_batch_program(batch, 5,
                                                             bad_mesh),
                   doc_lanes=batch.num_docs_padded * 2,
                   num_docs_padded=batch.num_docs_padded)


def planted_hbm_blowup() -> ToySpec:
    """A [docs, docs]-ish pairwise f64 temp: 2048×16384 f64 = 256 MiB live
    in one buffer — four DRR admission quanta for one query's scratch."""
    import jax.numpy as jnp

    def leaf(scores):
        pair = scores[:, None] * jnp.ones((1, 16384), jnp.float64)
        return pair.sum()

    return ToySpec(name="planted/hbm_blowup",
                   closed=_trace(leaf, ((2048,), np.float64)),
                   doc_lanes=2048, num_docs_padded=2048)


def planted_unbounded_bucket() -> list[ToySpec]:
    """A padding-bucket enumeration that grew past the pinned closure:
    per-request padded lengths mint per-request cache keys. The manifest
    pins two buckets; the 'corpus' now lowers three."""
    import jax.numpy as jnp

    def leaf(x):
        return jnp.sum(x * 2.0)

    return [ToySpec(name=f"planted/bucket/p{n}",
                    closed=_trace(leaf, ((n,), np.float32)),
                    doc_lanes=n, num_docs_padded=n,
                    cache_key=(("toy", n), False))
            for n in (1024, 2048, 4096)]


# --- the self-test -----------------------------------------------------------

def run_self_test() -> list[str]:
    """Returns a list of failure strings; empty means every planted defect
    was caught by exactly its own rule with a stable finding id."""
    failures: list[str] = []

    def expect(label, findings, rule, site_fragment):
        live = [f for f in findings if not f.suppressed]
        if not live:
            failures.append(f"{label}: planted defect NOT caught")
            return
        for f in live:
            if f.rule != rule:
                failures.append(
                    f"{label}: wrong rule {f.rule} (wanted {rule}): "
                    f"{f.message}")
            if site_fragment not in f.fid:
                failures.append(
                    f"{label}: unstable finding id {f.fid!r} "
                    f"(wanted fragment {site_fragment!r})")

    spec = planted_f64_upcast()
    expect("R2/f64_upcast", check_f64(spec), "R2", "planted/f64_upcast")
    if check_transfers(spec) or check_collectives(spec):
        failures.append("R2/f64_upcast: tripped unrelated rules")

    spec = planted_host_round_trip()
    expect("R3/host_round_trip", check_transfers(spec), "R3",
           "pure_callback")
    if check_f64(spec) or check_collectives(spec) or check_hbm(spec):
        failures.append("R3/host_round_trip: tripped unrelated rules")

    spec = planted_bad_collective()
    expect("R4/bad_collective", check_collectives(spec), "R4", "docs")

    spec = planted_mesh_axis_leak()
    expect("R4/mesh_axis_leak", check_collectives(spec), "R4", "rows")
    if check_transfers(spec):
        failures.append("R4/mesh_axis_leak: tripped unrelated rules")

    spec = planted_hbm_blowup()
    expect("R5/hbm_blowup", check_hbm(spec), "R5", "peak:")
    if check_transfers(spec) or check_collectives(spec):
        failures.append("R5/hbm_blowup: tripped unrelated rules")

    toys = planted_unbounded_bucket()
    from .audit import describe_programs
    for t in toys:
        t.peak = ir.liveness_peak(t.closed)
    programs = describe_programs(toys)
    pinned = manifest_from_programs(
        {k: v for k, v in list(sorted(programs.items()))[:2]})
    r1 = check_closure(programs, pinned)
    expect("R1/unbounded_bucket", r1, "R1", "closure:unpinned")

    # and the negative: a clean toy must stay clean
    import jax.numpy as jnp
    clean = ToySpec(name="planted/clean",
                    closed=_trace(lambda x: jnp.sum(x),
                                  ((1024,), np.float32)))
    for rule in (check_f64, check_transfers, check_collectives, check_hbm):
        extra = [f for f in rule(clean) if not f.suppressed]
        if extra:
            failures.append(
                f"clean program tripped {extra[0].rule}: {extra[0].message}")
    return failures
