"""CLI: `python -m tools.qwir audit|self-test`.

Exit codes follow qwlint: 0 clean, 1 findings (or self-test failures),
2 usage/internal error. `audit --write-manifest` regenerates the
compile-cache closure certificate (tools/qwir/manifest.json) — do that
only when a cache-key/jaxpr change is intentional, and update the pinned
program count in tests/test_qwir.py in the same commit.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path


def _setup_platform() -> None:
    # Mirror tests/conftest.py: force the CPU backend with 8 virtual
    # devices BEFORE jax initializes, so fused-mesh programs trace the
    # same way under the auditor as under the tier-1 suite.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass  # older jax: env vars above already took effect


def _cmd_audit(args) -> int:
    from .audit import default_manifest_path, run_audit
    manifest_path = Path(args.manifest) if args.manifest else \
        default_manifest_path()
    report = run_audit(manifest_path=manifest_path,
                       update_manifest=args.write_manifest)
    if args.sarif:
        from tools.sarif import write_sarif
        write_sarif(Path(args.sarif), tool="qwir",
                    rules={r: doc for r, doc in report.to_json()["rules"].items()},
                    results=[{"ruleId": f.rule, "id": f.fid,
                              "message": f.message, "site": f.site,
                              "suppressed": f.suppressed,
                              "justification": f.justification}
                             for f in report.findings])
    if args.json:
        json.dump(report.to_json(), sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(f"qwir: audited {report.program_count} lowered programs "
              f"({len(report.suppressed)} certified suppressions)")
        for f in report.unsuppressed:
            print(f"  {f.fid}\n    {f.message}")
        if report.ok:
            print("qwir: compile-cache closure certified; "
                  "no f64/transfer/collective/HBM findings")
    return 0 if report.ok else 1


def _cmd_self_test(args) -> int:
    from .selftest import run_self_test
    failures = run_self_test()
    if args.json:
        json.dump({"tool": "qwir-self-test", "ok": not failures,
                   "failures": failures}, sys.stdout, indent=1)
        sys.stdout.write("\n")
    elif failures:
        print("qwir self-test FAILED:")
        for line in failures:
            print(f"  {line}")
    else:
        print("qwir self-test: every planted defect caught by its rule")
    return 0 if not failures else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.qwir",
        description="jaxpr-level static auditor for the lowered leaf hot "
                    "path (rules R1-R5; see docs/static-analysis.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_audit = sub.add_parser("audit", help="audit the lowered plan corpus")
    p_audit.add_argument("--json", action="store_true",
                         help="emit the full report as JSON")
    p_audit.add_argument("--sarif", metavar="FILE",
                         help="also write a SARIF 2.1.0 log to FILE")
    p_audit.add_argument("--manifest", metavar="PATH",
                         help="closure manifest path (default: "
                              "tools/qwir/manifest.json)")
    p_audit.add_argument("--write-manifest", action="store_true",
                         help="regenerate the closure certificate from "
                              "the live corpus before checking")
    p_test = sub.add_parser("self-test",
                            help="verify each rule catches its planted "
                                 "defect")
    p_test.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)
    _setup_platform()
    try:
        if args.cmd == "audit":
            return _cmd_audit(args)
        return _cmd_self_test(args)
    except BrokenPipeError:
        return 2


if __name__ == "__main__":
    sys.exit(main())
