"""jaxpr introspection: recursive eqn walks, stable structural digests,
source-frame attribution, and a buffer-liveness peak-bytes walk.

Everything here consumes ClosedJaxprs produced by the abstract hooks
(`executor.abstract_program` & friends) — pure trace-time objects; nothing
in this module compiles or executes device code.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Iterator, Optional

import numpy as np

REPO_ROOT = Path(__file__).resolve().parents[2]


# --- recursive eqn walk ------------------------------------------------------

def _sub_jaxprs(params: dict):
    """Every (Closed)Jaxpr reachable from an eqn's params — pjit bodies,
    scan/while/cond branches, shard_map bodies, custom_* call jaxprs."""
    for v in params.values():
        for j in _jaxprs_in(v):
            yield j


def _jaxprs_in(v):
    # duck-typed: core.Jaxpr has .eqns/.invars, ClosedJaxpr wraps one in
    # .jaxpr — avoids importing jax internals whose paths move per version
    if hasattr(v, "eqns") and hasattr(v, "invars"):
        yield v
    elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
        yield v.jaxpr
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _jaxprs_in(item)


def iter_eqns(closed) -> Iterator[Any]:
    """Depth-first over every eqn, descending into sub-jaxprs."""
    stack = [closed.jaxpr]
    while stack:
        jaxpr = stack.pop()
        for eqn in jaxpr.eqns:
            yield eqn
            stack.extend(_sub_jaxprs(eqn.params))


def prim_base(name: str) -> str:
    """Primitive family name: jax suffixes rewrite generations with digits
    (`psum` → `psum2`); strip them so rule tables survive version bumps."""
    return name.rstrip("0123456789")


# --- aval helpers ------------------------------------------------------------

def aval_sig(aval) -> tuple:
    return (tuple(getattr(aval, "shape", ())), str(getattr(aval, "dtype", "?")))


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * np.dtype(dtype).itemsize


def var_avals(vs) -> list:
    return [v.aval for v in vs if hasattr(v, "aval")]


# --- source-frame attribution ------------------------------------------------

def repo_frame(eqn) -> Optional[tuple[str, str]]:
    """(repo-relative path, function name) of the innermost repo frame that
    bound this eqn, or None for eqns jax materialized with no user frame.
    Frames run innermost-first, so the first repo hit is the defining
    function — the anchor the certification registries key on."""
    tb = getattr(eqn.source_info, "traceback", None)
    if tb is None:
        return None
    root = str(REPO_ROOT) + "/"
    for fr in tb.frames:
        fname = fr.file_name
        if fname.startswith(root):
            return fname[len(root):], fr.function_name
    return None


# --- stable structural digest ------------------------------------------------

def jaxpr_digest(closed) -> str:
    """Hex digest of the program's structure: primitives, dataflow, avals,
    and params — NOT the pretty-printer output (which drifts across jax
    versions) and NOT object identities. Two traces of the same closure
    over the same ShapeDtypeStructs digest identically; any change to the
    lowered program (new eqn, dtype flip, shape change, param change)
    changes the digest."""
    h = hashlib.blake2b(digest_size=16)
    _digest_jaxpr(h, closed.jaxpr)
    for const in getattr(closed, "consts", ()) or ():
        arr = np.asarray(const)
        h.update(f"const:{arr.shape}:{arr.dtype}".encode())
        if arr.size <= 1024:
            h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _digest_jaxpr(h, jaxpr) -> None:
    env: dict[int, int] = {}

    def vid(v) -> str:
        if not hasattr(v, "aval"):  # DropVar etc.
            return "drop"
        if hasattr(v, "val"):  # Literal
            val = np.asarray(v.val)
            body = (np.ascontiguousarray(val).tobytes() if val.size <= 64
                    else str(val.shape).encode())
            return f"lit:{val.dtype}:{body!r}"
        return f"v{env.setdefault(id(v), len(env))}:{aval_sig(v.aval)}"

    h.update(("in:" + ",".join(vid(v) for v in jaxpr.invars)).encode())
    h.update(("const:" + ",".join(vid(v) for v in jaxpr.constvars)).encode())
    for eqn in jaxpr.eqns:
        h.update(f"|{eqn.primitive.name}".encode())
        h.update(("(" + ",".join(vid(v) for v in eqn.invars) + ")->("
                  + ",".join(vid(v) for v in eqn.outvars) + ")").encode())
        for key in sorted(eqn.params):
            val = eqn.params[key]
            subs = list(_jaxprs_in(val))
            if subs:
                h.update(f"{key}=jaxpr[".encode())
                for sub in subs:
                    _digest_jaxpr(h, sub)
                h.update(b"]")
            else:
                h.update(f"{key}={_stable_param(val)}".encode())
    h.update(("out:" + ",".join(vid(v) for v in jaxpr.outvars)).encode())


def _stable_param(v) -> str:
    """Params stringified without leaking object identities/addresses."""
    if isinstance(v, (bool, int, float, str, bytes, type(None))):
        return repr(v)
    if isinstance(v, (list, tuple)):
        inner = ",".join(_stable_param(x) for x in v)
        return f"({inner})" if isinstance(v, tuple) else f"[{inner}]"
    if isinstance(v, dict):
        # keys may be non-str (shard_map in/out_names map int → axis):
        # order by stringified key but index with the original
        items = sorted(v.items(), key=lambda kv: str(kv[0]))
        return "{" + ",".join(f"{k}:{_stable_param(val)}"
                              for k, val in items) + "}"
    if isinstance(v, np.dtype) or (isinstance(v, type)
                                   and issubclass(v, np.generic)):
        return str(np.dtype(v))
    if isinstance(v, np.ndarray):
        return f"ndarray{v.shape}:{v.dtype}"
    if hasattr(v, "axis_names"):  # Mesh / AbstractMesh
        return f"mesh{tuple(v.axis_names)}:{tuple(np.shape(v.devices)) if hasattr(v, 'devices') else ()}"
    # functions, shardings, effects, trees: type identity only — their
    # semantic content either shows up elsewhere (sub-jaxprs, avals) or is
    # not part of program structure
    return type(v).__name__


# --- buffer-liveness peak walk ----------------------------------------------

@dataclass
class PeakReport:
    peak_bytes: int
    input_bytes: int
    # largest single intermediate buffer and the repo frame that minted it
    largest_bytes: int = 0
    largest_site: str = ""


def liveness_peak(closed) -> PeakReport:
    """Upper-bound peak live bytes, by a last-use liveness scan over the
    eqn sequence (sub-jaxpr peaks charged at their call eqn on top of the
    caller's live set). Ignores XLA fusion/aliasing — i.e. this is what
    the program could hold if nothing fuses, the honest bound to check
    against an admission quantum."""
    jaxpr = closed.jaxpr
    input_bytes = sum(aval_bytes(a) for a in var_avals(jaxpr.invars))
    input_bytes += sum(int(np.asarray(c).nbytes)
                       for c in (getattr(closed, "consts", ()) or ()))
    report = PeakReport(peak_bytes=0, input_bytes=input_bytes)
    _walk_peak(jaxpr, input_bytes, report)
    return report


def _walk_peak(jaxpr, base_bytes: int, report: PeakReport) -> int:
    """Peak bytes while executing `jaxpr`, given `base_bytes` already live
    outside it (its inputs + enclosing frames). Returns the peak."""
    last_use: dict[int, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if hasattr(v, "aval") and not hasattr(v, "val"):
                last_use[id(v)] = i
    n_eqns = len(jaxpr.eqns)
    for v in jaxpr.outvars:
        if hasattr(v, "aval") and not hasattr(v, "val"):
            last_use[id(v)] = n_eqns
    live: dict[int, int] = {}
    cur = base_bytes
    peak = base_bytes
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            if not hasattr(v, "aval") or id(v) in live:
                continue
            b = aval_bytes(v.aval)
            live[id(v)] = b
            cur += b
            if b > report.largest_bytes:
                frame = repo_frame(eqn)
                report.largest_bytes = b
                report.largest_site = (f"{frame[0]}:{frame[1]}" if frame
                                       else eqn.primitive.name)
        inner_extra = 0
        for sub in _sub_jaxprs(eqn.params):
            # the sub-jaxpr's own invars alias buffers already counted in
            # `cur`; charge only its internal growth
            sub_peak = _walk_peak(sub, 0, report)
            inner_extra = max(inner_extra, sub_peak)
        peak = max(peak, cur + inner_extra)
        for v in eqn.invars:
            vk = id(v)
            if last_use.get(vk) == i and vk in live:
                cur -= live.pop(vk)
    report.peak_bytes = max(report.peak_bytes, peak)
    return peak
