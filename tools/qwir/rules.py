"""qwir rule engine: R2–R5 over one audited program's jaxpr.

R1 (compile-cache closure) is corpus-global and lives in audit.py; the
rules here are per-program and pure — they take a ProgramSpec-shaped
object exposing `.name`, `.closed`, `.mesh_axes`, `.doc_lanes`,
`.num_docs_padded` and return Findings.

Suppression model: R2 consults the in-code certification registries
(`QWIR_CERTIFIED_F64` dicts in ops/topk.py, search/executor.py,
parallel/fanout.py — the justification lives NEXT TO the kernel it
certifies). A certified hit is reported as a suppressed finding carrying
its justification; an uncertified hit fails the audit. Finding ids are
stable across runs: (rule, program, site) with no line numbers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from . import ir

# --- findings ----------------------------------------------------------------


@dataclass
class Finding:
    rule: str           # "R1".."R5"
    program: str        # corpus program name ("<corpus>" for R1 globals)
    site: str           # stable site id, e.g. "quickwit_tpu/ops/topk.py:exact_topk:sort"
    message: str
    suppressed: bool = False
    justification: str = ""

    @property
    def fid(self) -> str:
        return f"{self.rule}:{self.program}:{self.site}"

    def to_json(self) -> dict:
        out = {"rule": self.rule, "program": self.program, "site": self.site,
               "id": self.fid, "message": self.message,
               "suppressed": self.suppressed}
        if self.justification:
            out["justification"] = self.justification
        return out


RULE_DOCS = {
    "R1": "compile-cache-closure",
    "R2": "f64-promotion-leak",
    "R3": "host-round-trip",
    "R4": "collective-soundness",
    "R5": "hbm-ceiling",
}


# --- R2: f64 promotion leaks -------------------------------------------------

# doc-scale threshold for flagging f64 promotions: conversions of fewer
# elements (scalars, per-block bounds, top-k results) are not the hazard
# class; the 290ms bug class (PR 8) was corpus-scale f64 sorting
F64_PROMOTION_MIN_ELEMENTS = 4096

_SORT_PRIMS = {"sort", "top_k", "approx_top_k"}


def _certified_registries() -> dict[tuple[str, str], str]:
    """(repo path, function) -> justification, collected from the product
    modules' QWIR_CERTIFIED_F64 dicts so the suppression text stays inline
    with the kernel it certifies."""
    out: dict[tuple[str, str], str] = {}
    from quickwit_tpu.ops import topk as _topk
    from quickwit_tpu.search import executor as _executor
    from quickwit_tpu.parallel import fanout as _fanout
    for mod, path in ((_topk, "quickwit_tpu/ops/topk.py"),
                      (_executor, "quickwit_tpu/search/executor.py"),
                      (_fanout, "quickwit_tpu/parallel/fanout.py")):
        for func, why in getattr(mod, "QWIR_CERTIFIED_F64", {}).items():
            out[(path, func)] = why
    return out


def _site_of(eqn, fallback: str) -> str:
    frame = ir.repo_frame(eqn)
    return f"{frame[0]}:{frame[1]}" if frame else fallback


def check_f64(spec) -> list[Finding]:
    """f64 sort/top_k eqns and doc-scale promotions TO f64 must come from
    certified frames. The dtype lattice is scanned per eqn: a
    convert_element_type minting >= F64_PROMOTION_MIN_ELEMENTS f64
    elements, or any sort family eqn keyed on an f64 operand, is a hit."""
    certified = _certified_registries()
    findings: list[Finding] = []
    seen: set[str] = set()
    for eqn in ir.iter_eqns(spec.closed):
        base = ir.prim_base(eqn.primitive.name)
        hit = None
        if base in _SORT_PRIMS:
            f64_ops = [a for a in ir.var_avals(eqn.invars)
                       if str(getattr(a, "dtype", "")) == "float64"]
            if f64_ops:
                lanes = max(
                    (int(a.shape[-1]) if a.shape else 1) for a in f64_ops)
                hit = (f"{base}", f"f64-keyed {base} over {lanes}-lane "
                       f"operands")
        elif base == "convert_element_type":
            new_dtype = str(eqn.params.get("new_dtype", ""))
            out_avals = ir.var_avals(eqn.outvars)
            if new_dtype == "float64" and out_avals:
                n = 1
                for d in out_avals[0].shape:
                    n *= int(d)
                if n >= F64_PROMOTION_MIN_ELEMENTS:
                    hit = ("promote", f"promotes {n} elements to f64")
        if hit is None:
            continue
        kind, detail = hit
        frame = ir.repo_frame(eqn)
        site = (f"{frame[0]}:{frame[1]}:{kind}" if frame
                else f"<nosource>:{kind}")
        if site in seen:
            continue
        seen.add(site)
        why = certified.get(frame) if frame else None
        findings.append(Finding(
            rule="R2", program=spec.name, site=site,
            message=(f"{detail} at {site.rsplit(':', 1)[0]} — f64 work at "
                     "doc scale must run under a certified exact-fallback "
                     "site (QWIR_CERTIFIED_F64 registries)"),
            suppressed=why is not None, justification=why or ""))
    return findings


# --- R3: host round-trips ----------------------------------------------------

# primitive families that move data or control across the host boundary
# mid-program; any of these inside a leaf/fused program is a per-query
# host sync the packed-readback architecture exists to avoid
_TRANSFER_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "callback", "outside_call", "host_callback_call",
                   "infeed", "outfeed", "device_put", "copy_to_host"}


def check_transfers(spec) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[str] = set()
    for eqn in ir.iter_eqns(spec.closed):
        base = ir.prim_base(eqn.primitive.name)
        if base not in _TRANSFER_PRIMS and "callback" not in base:
            continue
        site = f"{_site_of(eqn, '<nosource>')}:{base}"
        if site in seen:
            continue
        seen.add(site)
        findings.append(Finding(
            rule="R3", program=spec.name, site=site,
            message=(f"host-boundary primitive `{eqn.primitive.name}` "
                     "inside the lowered program — every invocation pays a "
                     "device↔host round trip; the only sanctioned transfer "
                     "is the packed readback seam AFTER dispatch")))
    return findings


# --- R4: collective soundness ------------------------------------------------

_COLLECTIVE_PRIMS = {"psum", "pmax", "pmin", "all_gather", "all_to_all",
                     "ppermute", "pbroadcast", "reduce_scatter",
                     "psum_scatter", "axis_index", "pgather",
                     "all_gather_invariant"}


def _axis_names(params: dict):
    for key in ("axes", "axis_name", "axis_index_groups_axis"):
        v = params.get(key)
        if v is None:
            continue
        if isinstance(v, (list, tuple)):
            for a in v:
                if isinstance(a, str):
                    yield a
        elif isinstance(v, str):
            yield v


def check_collectives(spec) -> list[Finding]:
    """Every explicit collective must name an axis of the program's
    declared mesh, and shard_map bodies must bind exactly declared axes.
    (GSPMD-inserted collectives appear post-jaxpr and are keyed by the
    same NamedShardings the mesh dispatch passes — the explicit-eqn check
    here is what guards the shard_map root-merge programs ROADMAP item 1
    adds.)"""
    declared = set(spec.mesh_axes or ())
    findings: list[Finding] = []
    seen: set[str] = set()
    for eqn in ir.iter_eqns(spec.closed):
        base = ir.prim_base(eqn.primitive.name)
        names: set[str] = set()
        if base in _COLLECTIVE_PRIMS:
            names = set(_axis_names(eqn.params))
        elif base == "shard_map":
            mesh = eqn.params.get("mesh")
            names = set(getattr(mesh, "axis_names", ()) or ())
        else:
            continue
        bogus = names - declared
        if not names:
            bogus = {"<unnamed>"}
        if not bogus:
            continue
        site = f"{_site_of(eqn, '<nosource>')}:{base}:{','.join(sorted(bogus))}"
        if site in seen:
            continue
        seen.add(site)
        findings.append(Finding(
            rule="R4", program=spec.name, site=site,
            message=(f"collective `{eqn.primitive.name}` binds axis "
                     f"{sorted(bogus)} not in the declared mesh axes "
                     f"{sorted(declared)} — an undeclared axis either "
                     "fails at compile time on device or silently reduces "
                     "over the wrong replica group")))
    return findings


# --- R5: static HBM ceiling --------------------------------------------------

# fixed allowance for non-doc-scale state: agg bucket spaces (bounded by
# MAX_BUCKETS), top-k candidate sets, scalar temps
R5_FIXED_SLACK_BYTES = 16 << 20


def r5_ceiling_bytes(spec) -> int:
    from quickwit_tpu.ops.masks import QWIR_PEAK_PER_DOC_BYTES
    report = spec.peak  # computed once by the auditor
    return (report.input_bytes
            + QWIR_PEAK_PER_DOC_BYTES * int(spec.doc_lanes)
            + R5_FIXED_SLACK_BYTES)


def check_hbm(spec) -> list[Finding]:
    """Peak live bytes (liveness walk, fusion-free upper bound) must stay
    within the per-doc budget anchored in ops/masks.py AND within the DRR
    admission quantum — the unit HbmBudget schedules by; a program whose
    unfused liveness exceeds the quantum can stall admission for every
    queued tenant behind one query."""
    from quickwit_tpu.tenancy.drr import DEFAULT_QUANTUM_BYTES
    report = spec.peak
    findings: list[Finding] = []
    ceiling = r5_ceiling_bytes(spec)
    if report.peak_bytes > ceiling:
        findings.append(Finding(
            rule="R5", program=spec.name, site="peak:budget",
            message=(f"liveness peak {report.peak_bytes} B exceeds the "
                     f"per-doc budget ceiling {ceiling} B "
                     f"(inputs {report.input_bytes} B + "
                     f"{spec.doc_lanes} doc-lanes; largest buffer "
                     f"{report.largest_bytes} B from "
                     f"{report.largest_site or 'unknown'})")))
    quantum_ceiling = report.input_bytes + DEFAULT_QUANTUM_BYTES
    if report.peak_bytes > quantum_ceiling:
        findings.append(Finding(
            rule="R5", program=spec.name, site="peak:quantum",
            message=(f"liveness peak {report.peak_bytes} B exceeds the "
                     f"staged inputs plus one DRR admission quantum "
                     f"({quantum_ceiling} B) — admission cannot account "
                     "this program's scratch; largest buffer "
                     f"{report.largest_bytes} B from "
                     f"{report.largest_site or 'unknown'}")))
    return findings


PER_PROGRAM_RULES = (check_f64, check_transfers, check_collectives,
                     check_hbm)
