"""The representative plan corpus qwir audits.

Builds synthetic splits IN MEMORY (RamStorage) across the three supported
format versions (v3 default, v2 via QW_DISABLE_IMPACT, v1 via
QW_DISABLE_PACKED) and two padding buckets (1024- and 2048-doc padded),
then enumerates the lowered-program surface of the hot path:

  - single-split leaf programs: scoring term (posting-space path),
    bool+range filters, aggregation-only (k=0), column sorts, 2-key
    sorts, search_after pushdown, threshold pushdown (impact prefix +
    count_override), mask_override (PMaskRef), exact fallbacks
  - multi-query vmapped programs per batch bucket
  - fused multi-split batch programs (parallel/fanout.py, with and
    without 2-key / agg merges)
  - the Tier-A predicate-mask fill kernel

Every entry abstract-traces through the SAME build closures the dispatch
paths jit (executor.abstract_program / abstract_multi_program /
abstract_mask_fill, fanout.abstract_batch_program) and records the
mirrored compile-cache key — the R1 closure certificate is over exactly
the keys the runtime caches key on.

Determinism contract: same code + same jax ⇒ same program set, same
cache-key digests, same jaxpr digests. Everything here derives from
fixed literals and a seeded RNG; no wall clock, no host entropy.
"""

from __future__ import annotations

import hashlib
import os
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from . import ir

# --- corpus documents --------------------------------------------------------

T0 = 1_600_000_000
SEVERITIES = ("DEBUG", "INFO", "WARN", "ERROR")

# one padding bucket per entry: DOC_PAD=1024 ⇒ 220 docs pad to 1024,
# 1100 docs pad to 2048
SMALL_DOCS = 220
BIG_DOCS = 1100


def _mapper():
    from quickwit_tpu.models import DocMapper, FieldMapping, FieldType
    return DocMapper(
        field_mappings=[
            FieldMapping("timestamp", FieldType.DATETIME, fast=True,
                         input_formats=("unix_timestamp",)),
            FieldMapping("severity_text", FieldType.TEXT, tokenizer="raw",
                         fast=True),
            FieldMapping("tenant_id", FieldType.U64, fast=True),
            FieldMapping("body", FieldType.TEXT),
            FieldMapping("latency", FieldType.F64, fast=True),
        ],
        timestamp_field="timestamp",
        default_search_fields=("body",),
    )


def _docs(n: int, seed: int):
    rng = np.random.RandomState(seed)
    docs = []
    for i in range(n):
        docs.append({
            "timestamp": T0 + i * 60,
            "severity_text": SEVERITIES[int(rng.randint(0, 4))],
            "tenant_id": int(rng.randint(0, 4)),
            "body": " ".join(["alpha"] * int(rng.randint(1, 3))
                             + ["beta"] * int(rng.randint(0, 2))),
            "latency": float(rng.gamma(2.0, 40.0)),
        })
    return docs


@contextmanager
def _writer_env(**kv):
    old = {k: os.environ.get(k) for k in kv}
    os.environ.update({k: str(v) for k, v in kv.items()})
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _build_reader(mapper, docs, name: str, env: Optional[dict] = None):
    from quickwit_tpu.common.uri import Uri
    from quickwit_tpu.index import SplitReader, SplitWriter
    from quickwit_tpu.storage import RamStorage
    with _writer_env(**(env or {})):
        writer = SplitWriter(mapper)
        for doc in docs:
            writer.add_json_doc(doc)
        data = writer.finish()
    storage = RamStorage(Uri.parse("ram:///qwir"))
    storage.put(name, data)
    return SplitReader(storage, name)


# --- program specs -----------------------------------------------------------

@dataclass
class ProgramSpec:
    name: str                 # stable corpus id, e.g. "single/v3/term/k10"
    kind: str                 # single | multi | batch | mask_fill
    closed: Any               # ClosedJaxpr (abstract trace, never executed)
    cache_key: tuple          # the runtime compile-cache key, mirrored
    doc_lanes: int            # total padded doc lanes across vmap/batch dims
    num_docs_padded: int
    mesh_axes: tuple = ("splits", "docs")
    exact: bool = False
    peak: Any = None          # ir.PeakReport, filled by the auditor

    @property
    def cache_key_digest(self) -> str:
        return hashlib.blake2b(repr(self.cache_key).encode(),
                               digest_size=16).hexdigest()


def _queries():
    from quickwit_tpu.query.ast import Bool, MatchAll, Range, RangeBound, Term
    term = Term("body", "alpha")
    bool_range = Bool(
        must=(Term("severity_text", "ERROR"),),
        filter=(Range("timestamp",
                      lower=RangeBound((T0 + 600) * 10**6, True),
                      upper=RangeBound((T0 + 60 * SMALL_DOCS) * 10**6, False)),
                Range("tenant_id", lower=RangeBound(1, True),
                      upper=RangeBound(3, False))),
    )
    filter_only = Bool(
        filter=(Term("severity_text", "ERROR"),
                Range("tenant_id", lower=RangeBound(0, True),
                      upper=RangeBound(2, False))),
    )
    return term, bool_range, filter_only, MatchAll()


def _aggs():
    from quickwit_tpu.query.aggregations import DateHistogramAgg, MetricAgg
    return [
        DateHistogramAgg(name="per_hour", field="timestamp",
                         interval_micros=3_600 * 10**6,
                         sub_metrics=(MetricAgg("lat_avg", "avg", "latency"),)),
        MetricAgg("lat_stats", "stats", "latency"),
    ]


def build_corpus() -> list[ProgramSpec]:
    """Enumerate and abstract-trace the full plan corpus. Host-only: no
    XLA compile, no device execution, no data movement."""
    from quickwit_tpu.parallel import fanout
    from quickwit_tpu.search import executor
    from quickwit_tpu.search.plan import lower_request

    mapper = _mapper()
    small = _docs(SMALL_DOCS, seed=3)
    readers = {
        "v3": _build_reader(mapper, small, "v3.split"),
        "v2": _build_reader(mapper, small, "v2.split",
                            env={"QW_DISABLE_IMPACT": "1"}),
        "v1": _build_reader(mapper, small, "v1.split",
                            env={"QW_DISABLE_PACKED": "1"}),
        "v3big": _build_reader(mapper, _docs(BIG_DOCS, seed=5), "v3b.split"),
        "v3b": _build_reader(mapper, _docs(SMALL_DOCS, seed=7), "v3c.split"),
    }
    term, bool_range, filter_only, match_all = _queries()
    specs: list[ProgramSpec] = []

    def single(name, plan, k, exact=False):
        closed = executor.abstract_program(plan, k, exact)
        specs.append(ProgramSpec(
            name=name, kind="single", closed=closed,
            cache_key=executor.program_cache_key(plan, k, exact),
            doc_lanes=plan.num_docs_padded,
            num_docs_padded=plan.num_docs_padded, exact=exact))
        return plan

    # -- single-split leaf programs, across format versions + padding ----
    for ver in ("v1", "v2", "v3", "v3big"):
        plan = lower_request(term, mapper, readers[ver], [])
        single(f"single/{ver}/term/k10", plan, 10)
    for ver in ("v2", "v3"):
        plan = lower_request(bool_range, mapper, readers[ver], [],
                             sort_field="timestamp", sort_order="desc")
        single(f"single/{ver}/bool_range/k10", plan, 10)
    # aggregation-only (k=0 skips keying/top-k entirely)
    plan = lower_request(match_all, mapper, readers["v3"], _aggs())
    single("single/v3/aggs/k0", plan, 0)
    # count-only term
    plan = lower_request(term, mapper, readers["v3"], [])
    single("single/v3/term/k0", plan, 0)
    # column sort, ascending
    plan = lower_request(match_all, mapper, readers["v3"], [],
                         sort_field="latency", sort_order="asc")
    single("single/v3/sort_col/k5", plan, 5)
    # 2-key lexicographic sort (exact_topk_2key f64 anchor)
    plan = lower_request(match_all, mapper, readers["v3"], [],
                         sort_field="latency", sort_order="desc",
                         sort2_field="timestamp", sort2_order="asc")
    single("single/v3/sort_2key/k5", plan, 5)
    # search_after pushdown (marker value/doc ride traced scalars)
    plan = lower_request(match_all, mapper, readers["v3"], [],
                         sort_field="latency", sort_order="desc",
                         search_after=(123.5, None, "lt_tie", 7))
    single("single/v3/search_after/k5", plan, 5)
    # threshold pushdown over the scoring term: format v3 stages the
    # impact-ordered live prefix and sets count_override
    plan = lower_request(term, mapper, readers["v3"], [],
                         sort_value_threshold=2.0)
    single("single/v3/threshold/k10", plan, 10)
    # the certified exact-fallback program (guided_topk's unsafe-screen
    # re-dispatch lands here)
    plan = lower_request(term, mapper, readers["v3"], [])
    single("single/v3/term_exact/k10", plan, 10, exact=True)
    plan = lower_request(match_all, mapper, readers["v3"], [],
                         sort_field="latency", sort_order="asc")
    single("single/v3/sort_col_exact/k5", plan, 5, exact=True)
    # mask_override: Tier-A cached predicate stands in for the whole root
    padded = readers["v3"].num_docs_padded
    mask = np.zeros(padded, dtype=bool)
    mask[: SMALL_DOCS : 3] = True
    packed_mask = np.packbits(mask)
    plan = lower_request(filter_only, mapper, readers["v3"], [],
                         sort_field="timestamp", sort_order="desc",
                         mask_override=packed_mask,
                         mask_key="mask.qwir")
    single("single/v3/mask_override/k10", plan, 10)

    # -- chunked leaf programs (search/chunkexec.py) ---------------------
    # the resumable scan dispatches one compiled program per doc-block
    # slab; every chunk of a scan shares ONE program per (mode, span) —
    # chunk bounds and threshold updates ride scalar inputs, not traced
    # constants — so the closure grows exactly one entry per chunk mode
    from quickwit_tpu.index.format import DOC_PAD, POSTING_PAD
    from quickwit_tpu.search import chunkexec
    plan = lower_request(term, mapper, readers["v3big"], [])
    single("chunked/v3big/term_posting/k10",
           chunkexec.posting_chunk_plan(plan, 0, POSTING_PAD), 10)
    plan = lower_request(match_all, mapper, readers["v3big"], [],
                         sort_field="latency", sort_order="desc")
    single("chunked/v3big/sort_col_dense/k5",
           chunkexec.dense_chunk_plan(plan, 0, DOC_PAD), 5)

    # -- multi-query vmapped programs (one per batch bucket) -------------
    plan = lower_request(term, mapper, readers["v3"], [])
    for bucket in (2, 4):
        closed = executor.abstract_multi_program(plan, 10, bucket)
        specs.append(ProgramSpec(
            name=f"multi/v3/term/b{bucket}/k10", kind="multi", closed=closed,
            cache_key=executor.multi_program_cache_key(plan, 10, bucket),
            doc_lanes=plan.num_docs_padded * bucket,
            num_docs_padded=plan.num_docs_padded))

    # -- stacked query-group programs (device-side multi-query batching) -
    # DISTINCT shape-compatible queries as lanes of ONE program
    # (search/batcher.py QueryGroupPlanner → executor.dispatch_plan_stacked):
    # shared slots broadcast, query-shaped slots and scalars ride a leading
    # [Q] axis, and the [Q] validity mask is an operand — same kind/rule
    # treatment as the vmapped convoy programs ("multi")
    from quickwit_tpu.query.ast import Term as _Term
    sev_plans = [lower_request(_Term("severity_text", s), mapper,
                               readers["v3"], []) for s in ("ERROR", "INFO")]
    sev_sigs = {p.structure_digest(10) for p in sev_plans}
    assert len(sev_sigs) == 1, "corpus stacked lanes must be shape-compatible"
    closed = executor.abstract_stacked_program(sev_plans, 10)
    specs.append(ProgramSpec(
        name="stacked/v3/term/q2/k10", kind="multi", closed=closed,
        cache_key=executor.stacked_program_cache_key(sev_plans, 10),
        doc_lanes=sev_plans[0].num_docs_padded * 2,
        num_docs_padded=sev_plans[0].num_docs_padded))
    # stacked × chunked: the group scan dispatches chunk sub-plans of every
    # lane as one stacked program per chunk (chunkexec.execute_group_chunked)
    sev_chunks = [chunkexec.posting_chunk_plan(p, 0, POSTING_PAD)
                  for p in sev_plans]
    closed = executor.abstract_stacked_program(sev_chunks, 10)
    specs.append(ProgramSpec(
        name="stacked_chunked/v3/term_posting/q2/k10", kind="multi",
        closed=closed,
        cache_key=executor.stacked_program_cache_key(sev_chunks, 10),
        doc_lanes=sev_chunks[0].num_docs_padded * 2,
        num_docs_padded=sev_chunks[0].num_docs_padded))

    # -- fused multi-split batch programs (parallel/fanout.py) -----------
    from quickwit_tpu.search import SearchRequest, SortField

    def batch_spec(name, request, k, split_keys, aggs_note=""):
        rds = [readers[s] for s in split_keys]
        batch = fanout.build_batch(request, mapper, rds, list(split_keys))
        closed = fanout.abstract_batch_program(batch, k)
        specs.append(ProgramSpec(
            name=name, kind="batch", closed=closed,
            cache_key=fanout.batch_cache_key(batch, k, mesh=None),
            doc_lanes=batch.num_docs_padded * batch.n_splits,
            num_docs_padded=batch.num_docs_padded))

    batch_spec("batch/v3/term/n2/k10",
               SearchRequest(index_ids=["t"], query_ast=term, max_hits=10),
               10, ("v3", "v3b"))
    batch_spec("batch/v3/sort_2key/n2/k5",
               SearchRequest(index_ids=["t"], query_ast=match_all, max_hits=5,
                             sort_fields=[SortField("latency", "desc"),
                                          SortField("timestamp", "asc")]),
               5, ("v3", "v3b"))
    batch_spec("batch/v3/aggs/n2/k0",
               SearchRequest(
                   index_ids=["t"], query_ast=match_all, max_hits=0,
                   aggs={"per_hour": {
                       "date_histogram": {"field": "timestamp",
                                          "fixed_interval": "1h"},
                       "aggs": {"lat_avg": {"avg": {"field": "latency"}}}}}),
               0, ("v3", "v3b"))

    # -- collective mesh root-merge programs (parallel/fanout.py) --------
    # the whole-query shard_map programs: per-shard scoring, the pmax
    # threshold exchange, the all_gather + re-top-k merge, and the
    # psum/pmin/pmax agg reduction are EXPLICIT collective eqns here —
    # R4's mesh-axis rule audits every one against the declared
    # ("splits", "docs") axes
    def mesh_spec(name, request, k, split_keys, mesh):
        rds = [readers[s] for s in split_keys]
        batch = fanout.build_batch(request, mapper, rds, list(split_keys))
        closed = fanout.abstract_mesh_batch_program(batch, k, mesh)
        specs.append(ProgramSpec(
            name=name, kind="mesh", closed=closed,
            cache_key=fanout.batch_cache_key(batch, k, mesh=mesh),
            doc_lanes=batch.num_docs_padded * batch.n_splits,
            num_docs_padded=batch.num_docs_padded))

    mesh21 = fanout.make_mesh(2, 1)
    mesh22 = fanout.make_mesh(2, 2)
    mesh_spec("mesh/v3/term/n2/2x1/k10",
              SearchRequest(index_ids=["t"], query_ast=term, max_hits=10),
              10, ("v3", "v3b"), mesh21)
    mesh_spec("mesh/v3/sort_2key/n2/2x2/k5",
              SearchRequest(index_ids=["t"], query_ast=match_all, max_hits=5,
                            sort_fields=[SortField("latency", "desc"),
                                         SortField("timestamp", "asc")]),
              5, ("v3", "v3b"), mesh22)
    mesh_spec("mesh/v3/aggs/n2/2x1/k0",
              SearchRequest(
                  index_ids=["t"], query_ast=match_all, max_hits=0,
                  aggs={"per_hour": {
                      "date_histogram": {"field": "timestamp",
                                         "fixed_interval": "1h"},
                      "aggs": {"lat_avg": {"avg": {"field": "latency"}}}}}),
              0, ("v3", "v3b"), mesh21)

    # -- stacked query-group mesh program (query axis x splits x docs) ---
    # Q distinct queries over the SAME split set fused into one shard_map
    # dispatch: the query axis is vmapped inside every device shard, and
    # the pmax threshold exchange / all_gather merge / segment agg
    # reduction run per query lane — R4 audits the collectives against the
    # same ("splits", "docs") axes as the single-query mesh programs.
    # Range windows over the timestamp zonemap are shape-compatible by
    # construction (scalar bounds only; no per-query array operands).
    from quickwit_tpu.query.ast import Range as _Range, \
        RangeBound as _RangeBound

    def _window(lo_min, hi_min):
        return _Range("timestamp",
                      lower=_RangeBound((T0 + 60 * lo_min) * 10**6, True),
                      upper=_RangeBound((T0 + 60 * hi_min) * 10**6, False))

    group_batches = [
        fanout.build_batch(
            SearchRequest(index_ids=["t"], query_ast=_window(lo, hi),
                          max_hits=10,
                          sort_fields=[SortField("timestamp", "desc")]),
            mapper, [readers["v3"], readers["v3b"]], ["v3", "v3b"])
        for (lo, hi) in ((0, 120), (40, 200))]
    group_sigs = {b.template.signature(10) for b in group_batches}
    assert len(group_sigs) == 1, \
        "corpus query-group lanes must be shape-compatible"
    closed = fanout.abstract_group_mesh_program(group_batches, 10, mesh21)
    specs.append(ProgramSpec(
        name="group_mesh/v3/range/q2/n2/2x1/k10", kind="mesh", closed=closed,
        cache_key=fanout.group_cache_key(group_batches, 10, mesh=mesh21),
        doc_lanes=(group_batches[0].num_docs_padded
                   * group_batches[0].n_splits * 2),
        num_docs_padded=group_batches[0].num_docs_padded))

    # -- Tier-A predicate-mask fill kernel -------------------------------
    plan = lower_request(bool_range, mapper, readers["v3"], [],
                         sort_field="timestamp", sort_order="desc")
    closed = executor.abstract_mask_fill(plan)
    specs.append(ProgramSpec(
        name="mask_fill/v3/bool_range", kind="mask_fill", closed=closed,
        cache_key=executor.mask_fill_cache_key(plan),
        doc_lanes=plan.num_docs_padded,
        num_docs_padded=plan.num_docs_padded))

    names = [s.name for s in specs]
    assert len(names) == len(set(names)), "corpus program names must be unique"
    return specs
