"""qwir — jaxpr-level static auditing of the lowered leaf hot path.

qwlint (tools/qwlint) checks the *source*; qwmc (tools/qwmc) checks the
*protocols*; qwir checks the *artifact*: the lowered JAX programs the TPU
actually runs. It abstract-evals (never executes, never compiles) a
representative plan corpus — `search/plan.py` lowerings enumerated across
format versions, padding buckets, threshold/mask_override/count_override
variants, single-split / multi-query / fused-batch / mask-fill paths —
and runs five rules over the resulting jaxprs:

  R1 compile-cache-closure  the set of (cache key, jaxpr digest) pairs
                            over the corpus is finite and exactly matches
                            the checked-in manifest (pinned program count)
  R2 f64-promotion-leak     no f64 sorts / doc-scale f64 promotions in
                            leaf kernels outside certified sites
  R3 host-round-trip        no callback/transfer primitives inside any
                            audited program
  R4 collective-soundness   every collective names a live mesh axis
  R5 hbm-ceiling            buffer-liveness peak bytes within the per-doc
                            budget and the admission quantum

Entry point: `python -m tools.qwir audit` (see __main__.py).
"""

from .audit import run_audit  # noqa: F401
from .rules import Finding  # noqa: F401
