"""Audit orchestration: corpus → liveness → rules → R1 manifest closure.

The manifest (tools/qwir/manifest.json) is the compile-cache closure
certificate: one entry per corpus program carrying the mirrored runtime
cache-key digest and the structural jaxpr digest. `run_audit` recomputes
both over the live corpus and fails R1 on ANY drift — a new program, a
vanished program, a cache key that moved, or a lowered body that changed.
Intentional changes regenerate it via `python -m tools.qwir audit
--write-manifest` (and must update the pinned count in tests/test_qwir.py
— that is the review speed bump ROADMAP items 1/2 are required to hit).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from . import ir
from .rules import PER_PROGRAM_RULES, RULE_DOCS, Finding

MANIFEST_FORMAT = 1


def default_manifest_path() -> Path:
    return Path(__file__).resolve().parent / "manifest.json"


@dataclass
class AuditReport:
    findings: list[Finding] = field(default_factory=list)
    programs: dict[str, dict] = field(default_factory=dict)
    program_count: int = 0

    @property
    def unsuppressed(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def ok(self) -> bool:
        return not self.unsuppressed

    def to_json(self) -> dict:
        return {
            "tool": "qwir",
            "ok": self.ok,
            "program_count": self.program_count,
            "rules": RULE_DOCS,
            "findings": [f.to_json() for f in self.unsuppressed],
            "suppressed": [f.to_json() for f in self.suppressed],
            "programs": self.programs,
        }


def describe_programs(specs) -> dict[str, dict]:
    out = {}
    for spec in specs:
        out[spec.name] = {
            "kind": spec.kind,
            "cache_key": spec.cache_key_digest,
            "jaxpr": ir.jaxpr_digest(spec.closed),
            "eqns": sum(1 for _ in ir.iter_eqns(spec.closed)),
            "doc_lanes": int(spec.doc_lanes),
            "peak_bytes": int(spec.peak.peak_bytes) if spec.peak else 0,
            "input_bytes": int(spec.peak.input_bytes) if spec.peak else 0,
        }
    return out


def manifest_from_programs(programs: dict[str, dict]) -> dict:
    return {
        "format": MANIFEST_FORMAT,
        "program_count": len(programs),
        "programs": {
            name: {k: rec[k] for k in
                   ("kind", "cache_key", "jaxpr", "eqns", "doc_lanes")}
            for name, rec in sorted(programs.items())
        },
    }


def check_closure(programs: dict[str, dict],
                  manifest: Optional[dict]) -> list[Finding]:
    """R1: the recomputed (cache key, jaxpr digest) set must exactly match
    the checked-in manifest — finite, pinned, and closed."""
    findings: list[Finding] = []
    if manifest is None:
        findings.append(Finding(
            rule="R1", program="<corpus>", site="manifest:missing",
            message=("no compile-cache closure manifest — run "
                     "`python -m tools.qwir audit --write-manifest` and "
                     "check tools/qwir/manifest.json in")))
        return findings
    pinned = manifest.get("programs", {})
    if manifest.get("format") != MANIFEST_FORMAT:
        findings.append(Finding(
            rule="R1", program="<corpus>", site="manifest:format",
            message=f"manifest format {manifest.get('format')!r} != "
                    f"{MANIFEST_FORMAT}"))
    for name in sorted(set(pinned) - set(programs)):
        findings.append(Finding(
            rule="R1", program=name, site="closure:vanished",
            message=("program pinned in the manifest no longer lowers from "
                     "the corpus — a dispatch path died or the corpus "
                     "regressed; regenerate the manifest deliberately")))
    for name in sorted(set(programs) - set(pinned)):
        findings.append(Finding(
            rule="R1", program=name, site="closure:unpinned",
            message=("program compiles a cache entry not pinned in the "
                     "manifest — the compile-cache closure grew; audit the "
                     "new program and regenerate the manifest")))
    for name in sorted(set(programs) & set(pinned)):
        rec, pin = programs[name], pinned[name]
        if rec["cache_key"] != pin.get("cache_key"):
            findings.append(Finding(
                rule="R1", program=name, site="closure:cache_key",
                message=("runtime compile-cache key drifted from the "
                         "pinned certificate — plan signature or cache "
                         "keying changed; every deployed cache entry is a "
                         "cold compile until the manifest is regenerated")))
        if rec["jaxpr"] != pin.get("jaxpr"):
            findings.append(Finding(
                rule="R1", program=name, site="closure:jaxpr",
                message=("lowered program body drifted from the pinned "
                         "jaxpr digest (same cache key ⇒ silent behavior "
                         "change; different jax lowering ⇒ re-certify) — "
                         "regenerate the manifest after review")))
    declared = manifest.get("program_count")
    if declared != len(pinned):
        findings.append(Finding(
            rule="R1", program="<corpus>", site="closure:count",
            message=(f"manifest program_count {declared} does not match "
                     f"its own program table ({len(pinned)})")))
    return findings


def check_aliasing(programs: dict[str, dict]) -> list[Finding]:
    """R1 soundness: programs MAY share a compile-cache key (that is a
    cache hit — the v1 and v3 term plans lower identically), but then
    they must digest to the same jaxpr; a key collision across different
    bodies means dispatch hands one plan the other plan's executable."""
    findings: list[Finding] = []
    by_key: dict[str, dict[str, list[str]]] = {}
    for name, rec in sorted(programs.items()):
        by_key.setdefault(rec["cache_key"], {}) \
              .setdefault(rec["jaxpr"], []).append(name)
    for key_digest, bodies in sorted(by_key.items()):
        if len(bodies) > 1:
            names = sorted(n for group in bodies.values() for n in group)
            findings.append(Finding(
                rule="R1", program=names[0],
                site=f"closure:alias:{key_digest[:12]}",
                message=("compile-cache key collision across DIFFERENT "
                         f"lowered bodies: {names} share one cache entry "
                         "but trace to distinct jaxprs — the second to "
                         "compile silently runs the first one's "
                         "executable")))
    return findings


def load_manifest(path: Path) -> Optional[dict]:
    if not path.exists():
        return None
    return json.loads(path.read_text())


def write_manifest(path: Path, programs: dict[str, dict]) -> dict:
    manifest = manifest_from_programs(programs)
    path.write_text(json.dumps(manifest, indent=1, sort_keys=True) + "\n")
    return manifest


def audit_specs(specs) -> AuditReport:
    """Run the per-program rules (R2–R5) over already-built specs."""
    report = AuditReport(program_count=len(specs))
    for spec in specs:
        if spec.peak is None:
            spec.peak = ir.liveness_peak(spec.closed)
        for rule in PER_PROGRAM_RULES:
            report.findings.extend(rule(spec))
    report.programs = describe_programs(specs)
    report.findings.extend(check_aliasing(report.programs))
    return report


def run_audit(manifest_path: Optional[Path] = None,
              update_manifest: bool = False) -> AuditReport:
    """The full audit: build the corpus, run R2–R5, prove R1 closure."""
    from .corpus import build_corpus
    specs = build_corpus()
    report = audit_specs(specs)
    path = manifest_path or default_manifest_path()
    if update_manifest:
        write_manifest(path, report.programs)
    report.findings.extend(
        check_closure(
            {n: rec for n, rec in report.programs.items()},
            load_manifest(path)))
    return report
