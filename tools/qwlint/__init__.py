"""qwlint — codebase-specific static analysis for quickwit_tpu.

An AST-based analyzer (stdlib only) that encodes this repo's invariants
as lint rules. The two historical bug classes it targets are exactly the
ones PR 5 and ROADMAP item 1 paid for at runtime: typed control-flow
exceptions (deadline expiry, `OverloadShed`, `TenantRateLimited`,
injected faults) swallowed by broad `except Exception` catches, and
silent device→host readbacks (`float()` on a traced value is a full
`block_until_ready`) hiding in hot-path code.

Rules:
    QW001 hidden-host-readback        (hot-path modules only)
    QW002 recompilation-hazard        (per-call `jax.jit`, dynamic statics)
    QW003 ambient-context-propagation (bare callables across thread hops)
    QW004 swallowed-control-flow      (broad excepts on the query path)
    QW005 metrics-hygiene             (qw_ prefix, duplicates, cardinality)
    QW006 ambient-time-and-randomness (sim-scoped modules must use the
                                       virtualizable clock/rng seams)
    QW007 lock-order-hazard           (cross-file acquisition-graph cycles,
                                       device readbacks under a held lock)

Suppression: `# qwlint: disable=QW001` on the flagged line, on the
enclosing `def` line (covers the whole function), or
`# qwlint: disable-file=QW001` anywhere in the file (covers the file).
Grandfathered findings live in `tools/qwlint/baseline.json`, keyed by
(rule, path, function) with a count and a one-line justification — line
numbers are deliberately NOT part of the key so unrelated edits don't
churn the baseline, while any NEW finding in the same function trips it.

CLI: `python -m tools.qwlint quickwit_tpu/ [--baseline FILE] [--json]`.
Exit codes: 0 clean, 1 findings, 2 usage/internal error.
"""

from .core import (  # noqa: F401
    Finding,
    FileContext,
    analyze_file,
    analyze_paths,
    apply_baseline,
    default_baseline_path,
    load_baseline,
    write_baseline,
)
from .rules import RULES, RULE_DOCS  # noqa: F401
