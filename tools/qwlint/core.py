"""qwlint engine: file loading, suppression comments, baseline, runner.

Rules live in `tools/qwlint/rules.py`; this module owns everything
rule-independent so adding a rule never touches the engine (see
docs/static-analysis.md, "how to add a rule").
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional

_DISABLE_RE = re.compile(r"qwlint:\s*disable(?P<scope>-file|-next-line)?"
                         r"\s*=\s*(?P<ids>QW\d{3}(?:\s*,\s*QW\d{3})*)")
_RULE_ID_RE = re.compile(r"QW\d{3}")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str        # posix path relative to the analysis root
    line: int
    col: int
    function: str    # dotted qualname of the enclosing def, or "<module>"
    message: str

    def key(self) -> tuple:
        """Baseline identity: line numbers excluded on purpose so edits
        above a grandfathered site don't churn the baseline."""
        return (self.rule, self.path, self.function)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.function}] {self.message}")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "function": self.function,
                "message": self.message}


class LintError(Exception):
    """Unanalyzable input (syntax error, undecodable file)."""


def _parse_suppressions(source: str) -> tuple[dict[int, set], set]:
    """(line -> disabled rule ids, file-level disabled ids) from
    `# qwlint: disable=QW0xx[,QW0yy]` (this line),
    `# qwlint: disable-next-line=QW0xx` (the line below — for lines whose
    trailing-comment budget is spent) and `# qwlint: disable-file=QW0xx`
    comments. Trailing prose after the ids (a justification) is allowed."""
    per_line: dict[int, set] = {}
    whole_file: set = set()
    comment_only: set = set()
    pending_next: dict[int, set] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            if tok.line[:tok.start[1]].strip() == "":
                comment_only.add(tok.start[0])
            match = _DISABLE_RE.search(tok.string)
            if not match:
                continue
            ids = set(_RULE_ID_RE.findall(match.group("ids")))
            scope = match.group("scope")
            if scope == "-file":
                whole_file |= ids
            elif scope == "-next-line":
                pending_next.setdefault(tok.start[0], set()).update(ids)
            else:
                per_line.setdefault(tok.start[0], set()).update(ids)
    except tokenize.TokenError:
        pass  # partial comment map beats refusing to lint
    # "-next-line" targets the next CODE line: a justification wrapped
    # over several comment lines still lands on the statement below it
    for comment_line, ids in pending_next.items():
        target = comment_line + 1
        while target in comment_only:
            target += 1
        per_line.setdefault(target, set()).update(ids)
    return per_line, whole_file


def _annotate(tree: ast.AST) -> dict[int, ast.AST]:
    """Stamp every node with its enclosing qualname (`_qw_qual`), the def
    line numbers of the enclosing function stack (`_qw_funcs`) and its
    parent node (`_qw_parent`). Returns {def lineno -> FunctionDef}."""
    defs: dict[int, ast.AST] = {}

    def walk(node: ast.AST, qual: str, funcs: tuple) -> None:
        node._qw_qual = qual or "<module>"  # type: ignore[attr-defined]
        node._qw_funcs = funcs              # type: ignore[attr-defined]
        for child in ast.iter_child_nodes(node):
            child._qw_parent = node         # type: ignore[attr-defined]
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = f"{qual}.{child.name}" if qual else child.name
                defs[child.lineno] = child
                child._qw_qual = name       # type: ignore[attr-defined]
                child._qw_funcs = funcs + (child.lineno,)  # type: ignore
                # decorators run in the ENCLOSING scope at def time (a
                # module-level @partial(jax.jit, ...) compiles once, not
                # per call) — only the body belongs to the new function
                decorators = {id(d) for d in child.decorator_list}
                for sub in ast.iter_child_nodes(child):
                    sub._qw_parent = child  # type: ignore[attr-defined]
                    if id(sub) in decorators:
                        walk(sub, qual, funcs)
                    else:
                        walk(sub, name, funcs + (child.lineno,))
            elif isinstance(child, ast.ClassDef):
                name = f"{qual}.{child.name}" if qual else child.name
                walk(child, name, funcs)
            else:
                walk(child, qual, funcs)

    tree._qw_parent = None  # type: ignore[attr-defined]
    walk(tree, "", ())
    return defs


class FileContext:
    """Everything a rule needs about one file: the annotated tree, the
    suppression map, and a `shared` dict for cross-file rule state (the
    runner hands every file the same instance)."""

    def __init__(self, path: str, relpath: str, source: str,
                 shared: Optional[dict] = None):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        try:
            self.tree = ast.parse(source)
        except SyntaxError as exc:
            raise LintError(f"{relpath}: {exc}") from exc
        self.line_disables, self.file_disables = _parse_suppressions(source)
        self.defs_by_line = _annotate(self.tree)
        self.shared = shared if shared is not None else {}
        self.findings: list[Finding] = []

    # -- helpers for rules -------------------------------------------------
    def in_package_scope(self, patterns: Iterable[str]) -> bool:
        """True when this file is inside the named quickwit_tpu modules —
        or OUTSIDE quickwit_tpu entirely (fixture snippets and ad-hoc CLI
        targets are always in scope, so the rules stay testable)."""
        if "quickwit_tpu/" not in self.relpath:
            return True
        return any(p in self.relpath for p in patterns)

    def suppressed(self, rule: str, node: ast.AST) -> bool:
        if rule in self.file_disables:
            return True
        lines = {getattr(node, "lineno", 0)}
        lines.update(getattr(node, "_qw_funcs", ()))
        return any(rule in self.line_disables.get(line, ())
                   for line in lines)

    def add(self, rule: str, node: ast.AST, message: str) -> None:
        if self.suppressed(rule, node):
            return
        self.findings.append(Finding(
            rule=rule, path=self.relpath,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            function=getattr(node, "_qw_qual", "<module>"),
            message=message))

    def enclosing_def(self, node: ast.AST) -> Optional[ast.AST]:
        funcs = getattr(node, "_qw_funcs", ())
        return self.defs_by_line.get(funcs[-1]) if funcs else None

    def enclosing_defs(self, node: ast.AST) -> list[ast.AST]:
        return [self.defs_by_line[line]
                for line in getattr(node, "_qw_funcs", ())
                if line in self.defs_by_line]

    def statement_of(self, node: ast.AST) -> Optional[ast.stmt]:
        cur: Optional[ast.AST] = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = getattr(cur, "_qw_parent", None)
        return cur


def dotted_name(node: ast.AST) -> str:
    """`np.asarray` → "np.asarray"; unknown bases collapse to the attr
    chain that IS resolvable (`x[0].foo.item` → "item")."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def last_segment(node: ast.AST) -> str:
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else ""


# --- runner -----------------------------------------------------------------

def _iter_py_files(path: str):
    if os.path.isfile(path):
        yield path
        return
    for base, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for name in sorted(files):
            if name.endswith(".py"):
                yield os.path.join(base, name)


def analyze_file(path: str, root: Optional[str] = None,
                 shared: Optional[dict] = None) -> list[Finding]:
    from .rules import RULES
    root = root or os.getcwd()
    relpath = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    ctx = FileContext(path, relpath, source, shared=shared)
    for rule in RULES:
        rule.check(ctx)
    return ctx.findings


def analyze_paths(paths: Iterable[str],
                  root: Optional[str] = None) -> list[Finding]:
    """Lint every .py file under `paths`; relative paths in findings are
    against `root` (default: cwd, which the CLI sets to the repo root)."""
    from .rules import RULES
    shared: dict = {}
    findings: list[Finding] = []
    errors: list[str] = []
    for path in paths:
        for file_path in _iter_py_files(path):
            try:
                findings.extend(analyze_file(file_path, root=root,
                                             shared=shared))
            except LintError as exc:
                errors.append(str(exc))
    for rule in RULES:
        finalize = getattr(rule, "finalize", None)
        if finalize is not None:
            findings.extend(finalize(shared))
    if errors:
        raise LintError("; ".join(errors))
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


# --- baseline ---------------------------------------------------------------

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: str) -> list[dict]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data["entries"] if isinstance(data, dict) else data
    for entry in entries:
        for key in ("rule", "path", "function"):
            if key not in entry:
                raise LintError(f"baseline entry missing {key!r}: {entry}")
        entry.setdefault("count", 1)
        entry.setdefault("why", "")
    return entries


def apply_baseline(findings: list[Finding], entries: list[dict]
                   ) -> tuple[list[Finding], list[dict]]:
    """(new findings, stale entries). A finding is grandfathered while its
    (rule, path, function) key has remaining baseline count; if a function
    accrues MORE findings than its baselined count, every one of them is
    reported (an honest "this function regressed" signal beats guessing
    which of n identical-keyed findings is the new one)."""
    allowed: dict[tuple, int] = {}
    for entry in entries:
        key = (entry["rule"], entry["path"], entry["function"])
        allowed[key] = allowed.get(key, 0) + int(entry["count"])
    by_key: dict[tuple, list[Finding]] = {}
    for finding in findings:
        by_key.setdefault(finding.key(), []).append(finding)
    new: list[Finding] = []
    for key, group in by_key.items():
        if len(group) > allowed.get(key, 0):
            if allowed.get(key, 0):
                note = (f" ({len(group)} findings vs {allowed[key]} "
                        f"baselined in this function)")
                group = [Finding(f.rule, f.path, f.line, f.col, f.function,
                                 f.message + note) for f in group]
            new.extend(group)
    seen_keys = set(by_key)
    stale = [entry for entry in entries
             if (entry["rule"], entry["path"], entry["function"])
             not in seen_keys]
    return (sorted(new, key=lambda f: (f.path, f.line, f.col, f.rule)),
            stale)


def write_baseline(findings: list[Finding], path: str,
                   previous: Optional[list[dict]] = None) -> None:
    """Emit a baseline covering `findings`, carrying over `why` text from
    a previous baseline where the key still matches."""
    whys: dict[tuple, str] = {}
    for entry in previous or []:
        whys[(entry["rule"], entry["path"], entry["function"])] = \
            entry.get("why", "")
    counts: dict[tuple, int] = {}
    for finding in findings:
        counts[finding.key()] = counts.get(finding.key(), 0) + 1
    entries = [{"rule": rule, "path": rel, "function": func, "count": count,
                "why": whys.get((rule, rel, func), "TODO: justify or fix")}
               for (rule, rel, func), count in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"entries": entries}, fh, indent=2)
        fh.write("\n")
