"""The five qwlint rules. Each rule is an object with `id`, `title`, and
`check(ctx: FileContext)`; cross-file rules may also define
`finalize(shared) -> list[Finding]` which the runner calls once after
every file has been checked."""

from __future__ import annotations

import ast
import re
from typing import Optional

from .core import FileContext, Finding, dotted_name, last_segment

# --- QW001 hidden-host-readback ---------------------------------------------

_HOT_PATH_MODULES = (
    "quickwit_tpu/ops/",
    # explicit even though the ops/ prefix already covers it: the Pallas
    # kernels are the single hottest code in the tree and must not fall
    # out of scope if they ever move out of ops/
    "quickwit_tpu/ops/pallas/",
    # compaction merges re-run the impact quantizer over every surviving
    # posting; a hidden readback or per-merge jit there multiplies by the
    # merge fan-in, not the query rate
    "quickwit_tpu/compaction/",
    "quickwit_tpu/search/executor.py",
    "quickwit_tpu/search/leaf.py",
    "quickwit_tpu/search/collector.py",
    "quickwit_tpu/search/plan.py",
    # hierarchical cache tiers sit on the per-split hot path: a mask/agg
    # consult or fill must never smuggle in a device readback of its own
    "quickwit_tpu/search/mask_cache.py",
    "quickwit_tpu/search/agg_cache.py",
    "quickwit_tpu/search/tenant_cache.py",
    # write-time impact quantization: numpy-only by contract (its scores
    # must mirror ops/bm25.py bit-for-bit, and merge re-runs it per field)
    "quickwit_tpu/index/impact.py",
    # the audited host-decode seam: conversions are ALLOWED here (each is
    # individually suppressed with its contract), nowhere else
    "quickwit_tpu/search/hostdecode.py",
)

_READBACK_BUILTINS = {"float", "int", "bool"}
_READBACK_METHODS = {"item", "block_until_ready"}
_READBACK_DOTTED = {"np.asarray", "numpy.asarray", "jax.device_get"}


def _is_constantish(node: ast.AST) -> bool:
    """Literals and signed literals: `float("-inf")`, `int(-1)` are host
    constants, not readbacks."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp) and isinstance(node.operand,
                                                    ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_constantish(e) for e in node.elts)
    return False


class HiddenHostReadback:
    id = "QW001"
    title = "hidden-host-readback"

    def check(self, ctx: FileContext) -> None:
        if not ctx.in_package_scope(_HOT_PATH_MODULES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not getattr(node, "_qw_funcs", ()):
                continue  # module level runs at import time, not per query
            func = node.func
            if (isinstance(func, ast.Name)
                    and func.id in _READBACK_BUILTINS
                    and len(node.args) == 1 and not node.keywords
                    and not _is_constantish(node.args[0])):
                ctx.add(self.id, node,
                        f"{func.id}() on a possibly-device value forces a "
                        "device→host sync (ROADMAP item 1: readback_wait_ms "
                        "dominates the hot path); compute on device, move "
                        "it behind the packed readback seam, or suppress "
                        "with a justification if the value is already host "
                        "numpy")
                continue
            if (isinstance(func, ast.Attribute)
                    and func.attr in _READBACK_METHODS
                    and not node.args and not node.keywords):
                ctx.add(self.id, node,
                        f".{func.attr}() blocks on device completion and "
                        "copies to host; hot-path code must batch "
                        "readbacks through the packed seam "
                        "(search/executor.py::readback_plan_result)")
                continue
            name = dotted_name(func)
            if name in _READBACK_DOTTED and node.args \
                    and not _is_constantish(node.args[0]):
                ctx.add(self.id, node,
                        f"{name}() materializes its argument on host — a "
                        "silent transfer when the argument is a device "
                        "array; keep hot-path data device-resident")


# --- QW002 recompilation-hazard ---------------------------------------------

_CACHE_NAME_RE = re.compile(r"_CACHE")


def _is_jit_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    if name in ("jax.jit", "jit"):
        return True
    # functools.partial(jax.jit, ...) builds a jit factory
    if last_segment(node.func) == "partial" and node.args:
        return dotted_name(node.args[0]) in ("jax.jit", "jit")
    return False


class RecompilationHazard:
    id = "QW002"
    title = "recompilation-hazard"

    def check(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not _is_jit_call(node):
                continue
            self._check_static_args(ctx, node)
            if not getattr(node, "_qw_funcs", ()):
                continue  # module-level jit compiles once per process
            parent = getattr(node, "_qw_parent", None)
            if isinstance(parent, ast.Call) and parent.func is node:
                ctx.add(self.id, node,
                        "jax.jit(...)(...) creates and invokes a fresh "
                        "compiled callable per call — every query "
                        "recompiles; hoist the jitted callable to module "
                        "level or memoize it in a plan-keyed cache "
                        "(executor.py _JIT_CACHE pattern)")
                continue
            if self._reaches_cache(ctx, node):
                continue
            ctx.add(self.id, node,
                    "jax.jit created inside a function without a "
                    "*_CACHE store or builder return — if this runs per "
                    "query, each call pays a full XLA compile; memoize "
                    "keyed by plan structure, never by request values")

    @staticmethod
    def _check_static_args(ctx: FileContext, node: ast.Call) -> None:
        for kw in node.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            if not _static_spec_is_literal(kw.value):
                ctx.add(RecompilationHazard.id, node,
                        f"{kw.arg} computed at runtime: request-derived "
                        "values in static positions key the jit cache — "
                        "every distinct per-query value triggers a "
                        "recompile; statics must be plan-structure "
                        "constants")

    @staticmethod
    def _reaches_cache(ctx: FileContext, node: ast.Call) -> bool:
        """The builder idioms that are NOT hazards: the jit object is
        returned to a caller that caches it, or the enclosing function
        itself touches a *_CACHE name (memoizing getter)."""
        stmt = ctx.statement_of(node)
        if isinstance(stmt, ast.Return):
            return True
        for fn in ctx.enclosing_defs(node):
            for inner in ast.walk(fn):
                if isinstance(inner, ast.Name) \
                        and _CACHE_NAME_RE.search(inner.id):
                    return True
        return False


def _static_spec_is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(isinstance(e, ast.Constant) for e in node.elts)
    return False


# --- QW003 ambient-context-propagation --------------------------------------

_CTX_WRAPPERS = {"run_with_context", "bind_deadline", "bind_tenant",
                 "bind_profile"}


def _wrapped_names(tree: ast.AST) -> set[str]:
    """Names assigned from a wrapper call (`run = run_with_context(f)`) are
    wrapped callables too — the spawn site may be lines away."""
    names: set[str] = set()
    for node in ast.walk(tree):
        value = getattr(node, "value", None)
        if not (isinstance(node, (ast.Assign, ast.AnnAssign))
                and isinstance(value, ast.Call)
                and last_segment(value.func) in _CTX_WRAPPERS):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        names.update(t.id for t in targets if isinstance(t, ast.Name))
    return names


_POOL_RECEIVER_RE = re.compile(r"pool|executor", re.IGNORECASE)


def _is_pool_receiver(node: ast.AST) -> bool:
    """`.submit` is only a thread hop on pools/executors — a work-queue
    `.submit(task)` (compaction supervisor) takes data, not a callable."""
    if isinstance(node, ast.Call):
        node = node.func
    return bool(_POOL_RECEIVER_RE.search(last_segment(node) or ""))


def _is_wrapped_callable(node: ast.AST, wrapped: set[str]) -> bool:
    if isinstance(node, ast.Call) \
            and last_segment(node.func) in _CTX_WRAPPERS:
        return True
    return isinstance(node, ast.Name) and node.id in wrapped


class AmbientContextPropagation:
    id = "QW003"
    title = "ambient-context-propagation"

    def check(self, ctx: FileContext) -> None:
        wrapped = _wrapped_names(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            # "thread" covers the seam factory (common.sync.thread): the
            # contextvars hop is identical whichever constructor spawns it
            if last_segment(node.func) in ("Thread", "thread"):
                target = next((kw.value for kw in node.keywords
                               if kw.arg == "target"), None)
                if target is not None \
                        and not _is_wrapped_callable(target, wrapped):
                    ctx.add(self.id, node,
                            "threading.Thread(target=...) with a bare "
                            "callable: the new thread starts with EMPTY "
                            "contextvars, silently dropping the caller's "
                            "deadline/tenant/profile bindings — wrap the "
                            "target with common.ctx.run_with_context (or "
                            "suppress if the thread never serves a query)")
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "submit" and node.args
                    and _is_pool_receiver(node.func.value)):
                if not _is_wrapped_callable(node.args[0], wrapped):
                    ctx.add(self.id, node,
                            "executor.submit(fn, ...) with a bare "
                            "callable: pool worker threads do not inherit "
                            "contextvars — deadline/tenant/profile vanish "
                            "across the hop; wrap fn with "
                            "common.ctx.run_with_context")


# --- QW004 swallowed-control-flow -------------------------------------------

_QUERY_PATH_MODULES = (
    "quickwit_tpu/search/",
    "quickwit_tpu/serve/",
    "quickwit_tpu/storage/",
    "quickwit_tpu/parallel/",
    "quickwit_tpu/offload/",
)

_TYPED_CONTROL_FLOW = {"OverloadShed", "TenantRateLimited",
                       "DeadlineExceeded", "InjectedFault"}
# calling one of these inside the handler counts as classifying the
# exception rather than swallowing it
_CLASSIFIER_HELPERS = {"is_deadline_error", "classify_exception"}

_BROAD_NAMES = {"Exception", "BaseException"}


def _handler_type_names(handler: ast.ExceptHandler) -> set[str]:
    if handler.type is None:
        return set()
    nodes = handler.type.elts if isinstance(handler.type, ast.Tuple) \
        else [handler.type]
    return {last_segment(n) for n in nodes}


def _references_control_flow(handler: ast.ExceptHandler) -> bool:
    wanted = _TYPED_CONTROL_FLOW | _CLASSIFIER_HELPERS
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise) and node.exc is None:
            return True  # bare re-raise
        if isinstance(node, ast.Name) and node.id in wanted:
            return True
        if isinstance(node, ast.Attribute) and node.attr in wanted:
            return True
    return False


class SwallowedControlFlow:
    id = "QW004"
    title = "swallowed-control-flow"

    def check(self, ctx: FileContext) -> None:
        if not ctx.in_package_scope(_QUERY_PATH_MODULES):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            shielded = False
            for handler in node.handlers:
                names = _handler_type_names(handler)
                if names & _TYPED_CONTROL_FLOW:
                    shielded = True  # typed clause runs before the broad one
                    continue
                is_broad = handler.type is None or names & _BROAD_NAMES
                if not is_broad or shielded:
                    continue
                if _references_control_flow(handler):
                    continue
                ctx.add(self.id, handler,
                        "broad except on the query path swallows typed "
                        "control-flow exceptions (DeadlineExceeded, "
                        "OverloadShed, TenantRateLimited, InjectedFault) "
                        "into generic failures — re-raise them first "
                        "(`except (OverloadShed, TenantRateLimited): "
                        "raise`) or classify inside the handler")


# --- QW005 metrics-hygiene --------------------------------------------------

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_METRIC_OBSERVERS = {"inc", "observe", "set", "add"}
_METRIC_RECEIVER_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*$")
_HIGH_CARDINALITY_LABELS = {"query", "query_str", "doc_id", "split_id",
                            "trace_id", "span_id", "request_id", "path",
                            "uri", "url", "user", "opaque_id"}


class MetricsHygiene:
    id = "QW005"
    title = "metrics-hygiene"

    def check(self, ctx: FileContext) -> None:
        registrations = ctx.shared.setdefault("qw005_registrations", [])
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _METRIC_FACTORIES
                    and last_segment(func.value) == "METRICS"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                name = node.args[0].value
                if not name.startswith("qw_"):
                    ctx.add(self.id, node,
                            f"metric {name!r} is not qw_-prefixed — every "
                            "exported series must carry the namespace "
                            "prefix (reference: quickwit-metrics "
                            "new_counter! conventions)")
                registrations.append({
                    "name": name, "path": ctx.relpath,
                    "function": getattr(node, "_qw_qual", "<module>"),
                    "line": node.lineno, "col": node.col_offset,
                    "suppressed": ctx.suppressed(self.id, node)})
                continue
            if (isinstance(func, ast.Attribute)
                    and func.attr in _METRIC_OBSERVERS
                    and isinstance(func.value, ast.Name)
                    and _METRIC_RECEIVER_RE.match(func.value.id)):
                for kw in node.keywords:
                    if kw.arg in _HIGH_CARDINALITY_LABELS:
                        ctx.add(self.id, node,
                                f"label {kw.arg!r} is unbounded per-query "
                                "cardinality — each distinct value mints "
                                "a new series; aggregate, hash-bucket, or "
                                "drop the label (tenancy/registry.py "
                                "shows the bounded pattern)")
                    elif isinstance(kw.value, ast.JoinedStr):
                        ctx.add(self.id, node,
                                f"f-string value for label {kw.arg!r}: "
                                "interpolated label values are an "
                                "unbounded-cardinality trap; use a small "
                                "closed vocabulary")

    def finalize(self, shared: dict) -> list[Finding]:
        by_name: dict[str, list[dict]] = {}
        for reg in shared.get("qw005_registrations", []):
            by_name.setdefault(reg["name"], []).append(reg)
        findings = []
        for name, regs in sorted(by_name.items()):
            if len(regs) < 2:
                continue
            regs.sort(key=lambda r: (r["path"], r["line"]))
            first = regs[0]
            for reg in regs[1:]:
                if reg["suppressed"]:
                    continue
                findings.append(Finding(
                    rule=self.id, path=reg["path"], line=reg["line"],
                    col=reg["col"], function=reg["function"],
                    message=(f"metric {name!r} already registered at "
                             f"{first['path']}:{first['line']} — duplicate "
                             "registration either aliases state across "
                             "modules or raises TypeError on a type "
                             "mismatch at import time")))
        return findings


# --- QW006 ambient-time-and-randomness ---------------------------------------

# Modules the DST harness simulates: everything here must read time and
# randomness through quickwit_tpu/common/clock.py, or a seeded run is no
# longer deterministic (and scenario hours cost wall-clock hours). The
# clock seam itself (common/clock.py) is intentionally NOT scoped — it is
# the one place ambient time is allowed. External-source adapters
# (kinesis/aws_json/fake_sqs) and the sql metastore stay unscoped until
# they grow simulation coverage.
_SIM_SCOPED_MODULES = (
    "quickwit_tpu/common/actors.py",
    "quickwit_tpu/common/deadline.py",
    "quickwit_tpu/common/faults.py",
    "quickwit_tpu/common/tower.py",
    "quickwit_tpu/cluster/",
    "quickwit_tpu/control_plane/",
    "quickwit_tpu/dst/",
    "quickwit_tpu/indexing/cooperative.py",
    "quickwit_tpu/indexing/merge.py",
    "quickwit_tpu/indexing/pipeline.py",
    "quickwit_tpu/indexing/sources.py",
    "quickwit_tpu/ingest/ingester.py",
    "quickwit_tpu/ingest/router.py",
    "quickwit_tpu/ingest/wal.py",
    "quickwit_tpu/metastore/file_backed.py",
    "quickwit_tpu/models/index_metadata.py",
    "quickwit_tpu/models/split_metadata.py",
    "quickwit_tpu/observability/flight.py",
    "quickwit_tpu/observability/profiler.py",
    "quickwit_tpu/observability/slo.py",
    "quickwit_tpu/offload/",
    "quickwit_tpu/tenancy/overload.py",
)

_TIME_ATTRS = {"time", "monotonic", "sleep", "time_ns", "monotonic_ns",
               "perf_counter", "perf_counter_ns"}
# module-level random.* draws share one unseedable global stream;
# random.Random(seed) / random.SystemRandom() construction is fine
_RANDOM_ATTRS = {"random", "randint", "randrange", "randbytes", "choice",
                 "choices", "shuffle", "sample", "uniform", "gauss",
                 "getrandbits", "normalvariate", "expovariate",
                 "triangular", "betavariate", "paretovariate",
                 "vonmisesvariate", "weibullvariate", "lognormvariate"}
_DATETIME_DOTTED = {"datetime.now", "datetime.utcnow",
                    "datetime.datetime.now", "datetime.datetime.utcnow",
                    "date.today", "datetime.date.today"}


class AmbientTimeAndRandomness:
    id = "QW006"
    title = "ambient-time-and-randomness"

    def _message(self, what: str) -> str:
        return (f"direct {what} in a simulation-scoped module: the DST "
                "harness cannot virtualize it, so seeded runs stop being "
                "deterministic and scenario hours cost wall-clock hours — "
                "route through quickwit_tpu.common.clock (get_clock(), "
                "monotonic()/wall_time()/sleep(), get_rng())")

    def check(self, ctx: FileContext) -> None:
        if not ctx.in_package_scope(_SIM_SCOPED_MODULES):
            return
        if ctx.relpath.endswith("common/clock.py"):
            return  # the seam itself: ambient time is its job
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.level == 0:
                if node.module == "time":
                    bad = sorted(a.name for a in node.names
                                 if a.name in _TIME_ATTRS)
                    if bad:
                        ctx.add(self.id, node, self._message(
                            f"`from time import {', '.join(bad)}`"))
                elif node.module == "random":
                    bad = sorted(a.name for a in node.names
                                 if a.name in _RANDOM_ATTRS)
                    if bad:
                        ctx.add(self.id, node, self._message(
                            f"`from random import {', '.join(bad)}`"))
                continue
            if not isinstance(node, ast.Attribute):
                continue
            dotted = dotted_name(node)
            if dotted in _DATETIME_DOTTED:
                ctx.add(self.id, node, self._message(f"{dotted}()"))
            elif (isinstance(node.value, ast.Name)
                    and node.value.id == "time"
                    and node.attr in _TIME_ATTRS):
                # a bare reference (e.g. `clock=time.monotonic` default)
                # is as ambient as a call
                ctx.add(self.id, node, self._message(f"time.{node.attr}"))
            elif (isinstance(node.value, ast.Name)
                    and node.value.id == "random"
                    and node.attr in _RANDOM_ATTRS):
                ctx.add(self.id, node,
                        self._message(f"random.{node.attr}"))


# --- QW007 lock-order-hazard -------------------------------------------------

# A name is treated as a lock when its last dotted segment is `lock`/`mutex`
# or ends with `_lock`/`_LOCK` — matches `_MESH_DISPATCH_LOCK`, the batcher/
# budget/cache `self._lock`s and `shard.persist_lock`, but not `deadlock`
# or condition variables (which wrap a lock and are named `_cv`/`_cond`).
_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|mutex)$", re.IGNORECASE)

# Device syncs that must not run while a lock is held: every waiter on the
# lock stalls for a device round-trip it never asked for. Reuses QW001's
# readback sets; `jax.block_until_ready(x)` is the call-form spelling.
_QW007_READBACK_DOTTED = _READBACK_DOTTED | {"jax.block_until_ready"}

_QW007_SHARED = "qw007_edges"
# every edge, suppressed included: a suppression waives the CYCLE report,
# not the edge's existence — tools/qwrace's lock-graph bridge compares the
# runtime witness graph against this full static graph
_QW007_ALL_SHARED = "qw007_all_edges"


class LockOrder:
    """Cross-file lock-acquisition-order analysis.

    Collects an acquisition graph: an edge A → B means some function
    acquires B (via `with B:` or `B.acquire()`) while already holding A.
    After every file is checked, `finalize` reports each edge that sits on
    a cycle of two or more distinct locks — two threads taking the same
    pair in opposite orders is a deadlock waiting for scheduler timing.
    Self-edges are skipped: re-entering the *name* usually means two
    instances (per-shard `persist_lock`) or an RLock, not a self-deadlock.

    Also flags device readbacks executed while any lock is held: the
    readback's latency becomes every waiter's latency.
    """

    id = "QW007"
    title = "lock-order-hazard"

    # -- lock identity -----------------------------------------------------
    def _lock_id(self, ctx: FileContext, expr: ast.AST) -> Optional[str]:
        name = dotted_name(expr)
        if not name or not _LOCK_NAME_RE.search(name.rsplit(".", 1)[-1]):
            return None
        parts = name.split(".")
        if parts[0] in ("self", "cls"):
            # rewrite `self._lock` to `ClassName._lock` so every method of
            # the class contributes to one node in the graph
            qual = getattr(expr, "_qw_qual", "<module>")
            funcs = getattr(expr, "_qw_funcs", ())
            segments = [] if qual == "<module>" else qual.split(".")
            cls = ".".join(segments[:len(segments) - len(funcs)])
            parts[0] = cls or parts[0]
        return ".".join(parts)

    # -- recording ---------------------------------------------------------
    def _record_edge(self, ctx: FileContext, held: str, acquired: str,
                     node: ast.AST) -> None:
        if held == acquired:
            return
        site = {"path": ctx.relpath,
                "line": getattr(node, "lineno", 0),
                "col": getattr(node, "col_offset", 0),
                "function": getattr(node, "_qw_qual", "<module>")}
        ctx.shared.setdefault(_QW007_ALL_SHARED, {}) \
                  .setdefault((held, acquired), []).append(site)
        if ctx.suppressed(self.id, node):
            return
        ctx.shared.setdefault(_QW007_SHARED, {}) \
                  .setdefault((held, acquired), []).append(site)

    def _scan_readbacks(self, ctx: FileContext, exprs, held) -> None:
        if not held:
            return
        stack = list(exprs)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # runs later, not under this lock
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            hit = None
            if (isinstance(func, ast.Attribute)
                    and func.attr in _READBACK_METHODS
                    and not node.args and not node.keywords):
                hit = f".{func.attr}()"
            else:
                name = dotted_name(func)
                if name in _QW007_READBACK_DOTTED and node.args:
                    hit = f"{name}()"
            if hit:
                locks = ", ".join(lock for lock, _ in held)
                ctx.add(self.id, node,
                        f"{hit} forces a device→host sync while holding "
                        f"{locks}: every thread waiting on the lock stalls "
                        "for the device round-trip; move the readback "
                        "outside the critical section or suppress with the "
                        "ordering argument that makes holding it necessary")

    # -- ordered traversal -------------------------------------------------
    def _visit_block(self, ctx: FileContext, stmts, held) -> None:
        held = list(held)
        for stmt in stmts:
            held = self._visit_stmt(ctx, stmt, held)

    def _visit_stmt(self, ctx: FileContext, stmt: ast.stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._visit_block(ctx, stmt.body, [])  # runs with no locks held
            return held
        if isinstance(stmt, ast.ClassDef):
            self._visit_block(ctx, stmt.body, [])
            return held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._scan_readbacks(ctx, [i.context_expr for i in stmt.items],
                                 held)
            inner = list(held)
            for item in stmt.items:
                lock = self._lock_id(ctx, item.context_expr)
                if lock is None:
                    continue
                for outer, _ in inner:
                    self._record_edge(ctx, outer, lock, item.context_expr)
                inner.append((lock, item.context_expr))
            self._visit_block(ctx, stmt.body, inner)
            return held
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_readbacks(ctx, [stmt.test], held)
            self._visit_block(ctx, stmt.body, held)
            self._visit_block(ctx, stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_readbacks(ctx, [stmt.iter], held)
            self._visit_block(ctx, stmt.body, held)
            self._visit_block(ctx, stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.Try, getattr(ast, "TryStar", ast.Try))):
            self._visit_block(ctx, stmt.body, held)
            for handler in stmt.handlers:
                self._visit_block(ctx, handler.body, held)
            self._visit_block(ctx, stmt.orelse, held)
            self._visit_block(ctx, stmt.finalbody, held)
            return held
        # simple statement: explicit acquire()/release() bookkeeping, then
        # readback scan under whatever is held
        call = stmt.value if isinstance(stmt, ast.Expr) \
            and isinstance(stmt.value, ast.Call) else None
        if call is not None and isinstance(call.func, ast.Attribute):
            lock = self._lock_id(ctx, call.func.value)
            if lock is not None and call.func.attr == "acquire":
                for outer, _ in held:
                    self._record_edge(ctx, outer, lock, call)
                return held + [(lock, call)]
            if lock is not None and call.func.attr == "release":
                return [(name, site) for name, site in held
                        if name != lock]
        self._scan_readbacks(ctx, [stmt], held)
        return held

    def check(self, ctx: FileContext) -> None:
        self._visit_block(ctx, ctx.tree.body, [])

    # -- cross-file cycle report -------------------------------------------
    def finalize(self, shared: dict) -> list[Finding]:
        edges = shared.get(_QW007_SHARED, {})
        adjacency: dict[str, set] = {}
        for src, dst in edges:
            adjacency.setdefault(src, set()).add(dst)
        findings: list[Finding] = []
        for (src, dst), sites in sorted(edges.items()):
            path = self._shortest_path(adjacency, dst, src)
            if path is None:
                continue  # edge not on any cycle
            cycle = " → ".join([src] + path)
            for site in sites:
                findings.append(Finding(
                    rule=self.id, path=site["path"], line=site["line"],
                    col=site["col"], function=site["function"],
                    message=f"acquires {dst} while holding {src}, but "
                            f"elsewhere the order is reversed (cycle: "
                            f"{cycle}); two threads taking these locks in "
                            "opposite orders deadlock — pick one global "
                            "order and restructure the losing site"))
        return findings

    @staticmethod
    def _shortest_path(adjacency: dict, start: str,
                       goal: str) -> Optional[list]:
        """BFS path start → goal through the acquisition graph, or None."""
        frontier = [[start]]
        seen = {start}
        while frontier:
            next_frontier = []
            for path in frontier:
                if path[-1] == goal:
                    return path
                for succ in sorted(adjacency.get(path[-1], ())):
                    if succ not in seen:
                        seen.add(succ)
                        next_frontier.append(path + [succ])
            frontier = next_frontier
        return None


# --- QW008 raw-threading-construction ----------------------------------------

# constructors the sync seam wraps; Timer/Barrier are unused in the tree
# and would be findings too if they appeared
_QW008_CTORS = {"Lock", "RLock", "Condition", "Event", "Semaphore",
                "BoundedSemaphore", "Thread"}


class RawThreadingConstruction:
    """Raw `threading.{Lock,RLock,Condition,Event,Semaphore,Thread}`
    construction outside `common/sync.py`.

    The sync seam is how `tools/qwrace` gates every thread under one
    seeded scheduler and records happens-before edges: a raw primitive is
    invisible to race detection (its release→acquire edges are missing,
    so accesses it actually protects report as races) and — worse — a raw
    lock held across an instrumented preemption point can park its holder
    while another thread blocks on the real lock, hanging the gated run.
    Construct through `quickwit_tpu.common.sync` (`lock()/rlock()/
    condition()/event()/semaphore()/thread()`), or suppress with the
    argument that makes the site safe (leaf critical section containing
    no seam operations, process-lifetime infrastructure thread, ...).
    """

    id = "QW008"
    title = "raw-threading-construction"

    def _message(self, what: str) -> str:
        return (f"raw {what} outside common/sync.py: invisible to the "
                "qwrace scheduler and happens-before detection — "
                "construct via quickwit_tpu.common.sync "
                "(lock()/rlock()/condition()/event()/semaphore()/"
                "thread()), or suppress with the argument that makes the "
                "raw primitive safe here")

    def check(self, ctx: FileContext) -> None:
        if not ctx.in_package_scope(("quickwit_tpu/",)):
            return
        if ctx.relpath.endswith("common/sync.py"):
            return  # the seam itself: raw construction is its job
        from_imports: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.level == 0 \
                    and node.module == "threading":
                from_imports.update(
                    a.asname or a.name for a in node.names
                    if a.name in _QW008_CTORS)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            dotted = dotted_name(func)
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "threading"
                    and func.attr in _QW008_CTORS):
                ctx.add(self.id, node,
                        self._message(f"threading.{func.attr}()"))
            elif (isinstance(func, ast.Name) and dotted in from_imports):
                ctx.add(self.id, node, self._message(f"{dotted}()"))


RULES = [HiddenHostReadback(), RecompilationHazard(),
         AmbientContextPropagation(), SwallowedControlFlow(),
         MetricsHygiene(), AmbientTimeAndRandomness(), LockOrder(),
         RawThreadingConstruction()]

RULE_DOCS = {rule.id: rule.title for rule in RULES}
