"""CLI: `python -m tools.qwlint [paths...]`.

Exit-code contract (consumed by tests/test_qwlint.py and CI):
    0  no findings beyond the baseline
    1  at least one new finding
    2  usage error or unanalyzable input (syntax error)

The checked-in baseline (tools/qwlint/baseline.json) is applied by
default; `--no-baseline` shows everything, `--baseline FILE` swaps it,
`--write-baseline FILE` regenerates one (carrying over justifications
for keys that still match) for the adopt-then-ratchet workflow.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

from .core import (LintError, analyze_paths, apply_baseline,
                   default_baseline_path, load_baseline, write_baseline)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="qwlint",
        description="codebase-specific static analysis for quickwit_tpu")
    parser.add_argument("paths", nargs="*", default=["quickwit_tpu"],
                        help="files or directories to lint "
                             "(default: quickwit_tpu)")
    parser.add_argument("--root", default=None,
                        help="directory finding paths are relative to "
                             "(default: cwd)")
    parser.add_argument("--baseline", default=None,
                        help="baseline JSON to grandfather findings "
                             "(default: tools/qwlint/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline; report every finding")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON array on stdout")
    parser.add_argument("--write-baseline", default=None, metavar="FILE",
                        help="write a baseline covering current findings "
                             "and exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="rewrite the active baseline with stale "
                             "entries (fixed findings) removed — the "
                             "ratchet tightens itself")
    parser.add_argument("--sarif", default=None, metavar="FILE",
                        help="also write a SARIF 2.1.0 log to FILE")
    args = parser.parse_args(argv)

    if args.prune_baseline and args.no_baseline:
        print("qwlint: --prune-baseline conflicts with --no-baseline",
              file=sys.stderr)
        return 2

    paths = args.paths or ["quickwit_tpu"]
    try:
        findings = analyze_paths(paths, root=args.root)
    except LintError as exc:
        print(f"qwlint: error: {exc}", file=sys.stderr)
        return 2

    entries = []
    if not args.no_baseline:
        baseline_path = args.baseline or default_baseline_path()
        if os.path.exists(baseline_path):
            try:
                entries = load_baseline(baseline_path)
            except (LintError, json.JSONDecodeError, OSError) as exc:
                print(f"qwlint: bad baseline {baseline_path}: {exc}",
                      file=sys.stderr)
                return 2
        elif args.baseline is not None:
            print(f"qwlint: baseline not found: {baseline_path}",
                  file=sys.stderr)
            return 2

    if args.write_baseline:
        write_baseline(findings, args.write_baseline, previous=entries)
        print(f"qwlint: wrote baseline with {len(findings)} findings to "
              f"{args.write_baseline}")
        return 0

    new, stale = apply_baseline(findings, entries)

    if args.prune_baseline and stale:
        baseline_path = args.baseline or default_baseline_path()
        stale_keys = {(e["rule"], e["path"], e["function"]) for e in stale}
        kept = [e for e in entries
                if (e["rule"], e["path"], e["function"]) not in stale_keys]
        with open(baseline_path, "w", encoding="utf-8") as fh:
            json.dump({"entries": kept}, fh, indent=2)
            fh.write("\n")
        print(f"qwlint: pruned {len(stale)} stale entr(y/ies) from "
              f"{baseline_path} ({len(kept)} remain)", file=sys.stderr)
        stale = []

    if args.sarif:
        from tools.sarif import write_sarif
        from .rules import RULES
        write_sarif(
            Path(args.sarif), tool="qwlint",
            rules={r.id: r.title for r in RULES},
            results=[{"ruleId": f.rule, "message": f.message,
                      "file": f.path, "line": f.line,
                      "id": f"{f.rule}:{f.path}:{f.function}"}
                     for f in new])

    if args.as_json:
        print(json.dumps([f.to_dict() for f in new], indent=2))
    else:
        for finding in new:
            print(finding.render())
        for entry in stale:
            print(f"qwlint: note: stale baseline entry (fixed? remove it): "
                  f"{entry['rule']} {entry['path']} {entry['function']}",
                  file=sys.stderr)
        baselined = len(findings) - len(new)
        print(f"qwlint: {len(new)} finding(s), {baselined} baselined, "
              f"{len(stale)} stale baseline entr(y/ies)", file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
